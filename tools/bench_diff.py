#!/usr/bin/env python
"""Compare two bench artifacts (BENCH_r*.json) and gate on regression.

The bench history is the repo's perf ledger; nothing so far CHECKED it
— a throughput or MFU slide between rounds only surfaced when a human
re-read the numbers. This is the post-bench gate ("Benchmarking as a
gate", docs/perf.md)::

    python tools/bench_diff.py BENCH_r05.json BENCH_r06.json

Accepts either the harness wrapper format (the ``parsed`` key holds
the authoritative metric dict) or raw bench stdout (JSON lines — the
LAST parseable line is authoritative, bench.py's own convention).

Compared metrics, with direction and default tolerance:

- ``throughput`` (the headline ``value``)  — lower is a regression (5%)
- ``mfu``                                  — lower is a regression (5%)
- ``xla_temp_bytes``                       — higher is a regression (10%:
  post-donation the number is small enough that assignment-packing
  noise between XLA revisions exceeds the old 5%)
- ``xla_live_bytes`` (steady-state per-dispatch footprint: args + temp
  + outputs minus donated-alias bytes)     — higher is a regression (10%
  — a donation regression shows up here first)
- ``opt_state_bytes_per_device`` (the sharded weight update's
  per-device optimizer-state footprint)   — higher is a regression (10%)
- ``compile_s`` (cold compile)             — higher is a regression (25%,
  compile time is the noisiest of the set)
- ``serving_p99_ms`` (the serving bench's closed-loop request tail
  latency)                                 — higher is a regression (10%)
- ``serving_queue_wait_p50_ms`` (median time a request sits in the
  batcher queue before its dispatch)       — higher is a regression (10%)
- ``final_loss`` (the run ledger's last banked loss,
  telemetry/ledger.py)                     — higher is a regression (5%;
  a non-finite candidate loss is a regression outright — a diverged
  run must not bank as a healthy throughput number)
- ``goodput_pct`` (the goodput ledger's productive share of wall-clock,
  telemetry/goodput.py)                    — lower is a regression (5%:
  the same throughput with more time lost to compile/input/checkpoint
  badput is a worse run even when the step time held)
- ``bytes_on_wire_per_step`` (gradient bytes per sync step, the
  quantized-collectives plane)             — higher is a regression (10%:
  the collective traffic regrew, e.g. compression silently disengaged)
- ``mem_headroom_pct`` (the memory plane's device-bytes safety margin,
  telemetry/memory.py)                     — lower is a regression (10%:
  the program's HBM footprint grew toward the limit even when the step
  time held — the next model tweak OOMs instead of landing)
- ``host_overhead_pct`` (the step timeline's host-side share of the
  step, telemetry/timeline.py)             — higher is a regression (10%:
  host-side work — stats fetch, checkpoint commit, kvstore traffic —
  crept into the step where the device used to overlap it)

A delta past tolerance in the bad direction prints REGRESSION and the
exit code is 1 — wire it straight into CI after a bench round.
Improvements never fail. A metric missing on either side is a SKIP,
rendered in the table and recapped in a trailing note — never a
silent pass (a baseline that predates a metric is visible evidence,
not an accidental green). Runs that are not config-comparable (metric
name, platform, batch or steps_per_call differ — e.g. one round banked
the CPU fallback) are reported and exit 0, because a fallback round is
not evidence of a perf regression; ``--strict`` turns that into exit 3.
"""
import argparse
import json
import math
import sys

# metric -> (extractor, bad_direction, default_tol_pct)
# bad_direction: -1 = a DROP is a regression, +1 = a RISE is one
_DEF_TOL = {'throughput': 5.0, 'mfu': 5.0, 'xla_temp_bytes': 10.0,
            'xla_live_bytes': 10.0,
            'opt_state_bytes_per_device': 10.0, 'compile_s': 25.0,
            'serving_p99_ms': 10.0, 'serving_queue_wait_p50_ms': 10.0,
            'final_loss': 5.0, 'goodput_pct': 5.0,
            'bytes_on_wire_per_step': 10.0, 'mem_headroom_pct': 10.0,
            'host_overhead_pct': 10.0}
_DIRECTION = {'throughput': -1, 'mfu': -1, 'xla_temp_bytes': +1,
              'xla_live_bytes': +1,
              'opt_state_bytes_per_device': +1, 'compile_s': +1,
              'serving_p99_ms': +1, 'serving_queue_wait_p50_ms': +1,
              'final_loss': +1, 'goodput_pct': -1,
              'bytes_on_wire_per_step': +1, 'mem_headroom_pct': -1,
              'host_overhead_pct': +1}
_ORDER = ('throughput', 'mfu', 'xla_temp_bytes', 'xla_live_bytes',
          'opt_state_bytes_per_device', 'compile_s', 'serving_p99_ms',
          'serving_queue_wait_p50_ms', 'final_loss', 'goodput_pct',
          'bytes_on_wire_per_step', 'mem_headroom_pct',
          'host_overhead_pct')


def load_bench(path):
    """The authoritative metric dict out of one bench artifact."""
    with open(path) as f:
        text = f.read()
    try:
        data = json.loads(text)
        if isinstance(data, dict):
            # harness wrapper: {'n':…, 'rc':…, 'parsed': {...}} — or a
            # bare metric dict already. A failed round has parsed=None;
            # its banked JSON line may still be in the log tail
            if 'parsed' in data:
                if isinstance(data['parsed'], dict):
                    return data['parsed']
                for line in reversed(str(data.get('tail') or '')
                                     .strip().splitlines()):
                    try:
                        d = json.loads(line)
                        if isinstance(d, dict) and 'metric' in d:
                            return d
                    except ValueError:
                        continue
                raise SystemExit(
                    'bench_diff: %s is a failed bench round (no parsed '
                    'metric dict, none recoverable from its log tail)'
                    % path)
            return data
    except ValueError:
        pass
    # raw bench stdout: JSON lines, last parseable METRIC line wins —
    # a trailing auxiliary JSON object must not silently replace the
    # bench record and defuse the gate as 'not comparable'
    for line in reversed(text.strip().splitlines()):
        try:
            d = json.loads(line)
            if isinstance(d, dict) and 'metric' in d:
                return d
        except ValueError:
            continue
    raise SystemExit('bench_diff: %s holds no parseable bench JSON'
                     % path)


def _compile_s(rec):
    cc = rec.get('compile_cache') or {}
    for k in ('cold_s', 'compile_s'):
        if cc.get(k) is not None:
            return float(cc[k])
    return None


def extract(rec):
    """{metric: value} for the compared metrics (absent ones omitted)."""
    out = {}
    if rec.get('value') is not None:
        out['throughput'] = float(rec['value'])
    if rec.get('mfu') is not None:
        out['mfu'] = float(rec['mfu'])
    if rec.get('xla_temp_bytes'):
        out['xla_temp_bytes'] = float(rec['xla_temp_bytes'])
    if rec.get('xla_live_bytes'):
        out['xla_live_bytes'] = float(rec['xla_live_bytes'])
    # `is not None`, not truthiness: a stateless optimizer's honest 0
    # must stay gated (a regrowth from 0 is exactly a regression)
    if rec.get('opt_state_bytes_per_device') is not None:
        out['opt_state_bytes_per_device'] = \
            float(rec['opt_state_bytes_per_device'])
    c = _compile_s(rec)
    if c is not None:
        out['compile_s'] = c
    # serving tail latency (bench.py run_serving_bench): higher = a
    # regression in the continuous-batching plane
    if rec.get('serving_p99_ms') is not None:
        out['serving_p99_ms'] = float(rec['serving_p99_ms'])
    # serving queue wait (the tracing plane's per-stage breakdown):
    # a rise means requests sit in the batcher longer before their
    # dispatch — the batching economics regressed even if device
    # latency held
    if rec.get('serving_queue_wait_p50_ms') is not None:
        out['serving_queue_wait_p50_ms'] = \
            float(rec['serving_queue_wait_p50_ms'])
    # the run ledger's last banked loss (bench feeds telemetry/ledger):
    # convergence gate next to the throughput gates — a faster step
    # that stopped learning is a regression
    if rec.get('final_loss') is not None:
        out['final_loss'] = float(rec['final_loss'])
        # not a gated metric — comparability context for final_loss
        # (bench scales its step count to measured throughput)
        if rec.get('final_loss_step') is not None:
            out['final_loss_step'] = int(rec['final_loss_step'])
    # goodput (telemetry/goodput.py): the productive share of the bench
    # process's wall-clock — a DROP is the regression (more badput)
    if rec.get('goodput_pct') is not None:
        out['goodput_pct'] = float(rec['goodput_pct'])
    # gradient bytes per sync step (parallel/compression.py): a RISE
    # means the collective traffic regrew — e.g. quantization silently
    # disengaged. Improvements (compression landing) never fail; a
    # baseline that predates the gauge is a visible skip.
    if rec.get('bytes_on_wire_per_step') is not None:
        out['bytes_on_wire_per_step'] = \
            float(rec['bytes_on_wire_per_step'])
    # device-bytes headroom (telemetry/memory.py): a DROP means the
    # footprint crept toward the limit — the regression that OOMs the
    # NEXT change rather than this one
    if rec.get('mem_headroom_pct') is not None:
        out['mem_headroom_pct'] = float(rec['mem_headroom_pct'])
    # host-side share of the step (telemetry/timeline.py): a RISE means
    # fetch/checkpoint/kvstore work stopped overlapping the device —
    # the step got slower for a reason throughput alone may hide
    if rec.get('host_overhead_pct') is not None:
        out['host_overhead_pct'] = float(rec['host_overhead_pct'])
    return out


def comparability(a, b):
    """Reasons the two runs are not config-comparable ([] = they are).
    A CPU-fallback round (r02/r04 in the bench history) must not read
    as a 'regression' against a device round."""
    reasons = []
    for key in ('metric', 'platform', 'batch', 'steps_per_call'):
        va, vb = a.get(key), b.get(key)
        if va != vb:
            reasons.append('%s: %r vs %r' % (key, va, vb))
    return reasons


def diff(old, new, tols):
    """Rows [(metric, old, new, delta_pct, tol_pct, verdict)] — verdict
    'REGRESSION' when past tolerance in the bad direction."""
    mo, mn = extract(old), extract(new)
    rows = []
    for metric in _ORDER:
        vo, vn = mo.get(metric), mn.get(metric)
        if vo is None or vn is None:
            if vn is not None:
                # no baseline: the candidate carries a metric the old
                # round never banked — gate-able only from next round
                rows.append((metric, vo, vn, None, tols[metric],
                             'skipped (no baseline)'))
            elif vo is not None:
                rows.append((metric, vo, vn, None, tols[metric],
                             'skipped (missing in new run)'))
            continue
        if not math.isfinite(vn):
            # a nan/inf candidate (a diverged run's final_loss) can
            # never pass a tolerance comparison by accident
            rows.append((metric, vo, vn, None, tols[metric],
                         'REGRESSION (non-finite)'))
            continue
        if not math.isfinite(vo):
            # a nan baseline (a diverged run got banked) can't gate
            # anything: a visible skip, never an 'ok' from a nan delta
            rows.append((metric, vo, vn, None, tols[metric],
                         'skipped (baseline non-finite)'))
            continue
        if metric == 'final_loss':
            so, sn = mo.get('final_loss_step'), mn.get('final_loss_step')
            if so is not None and sn is not None and so != sn:
                # the runs trained different step counts (bench scales
                # steps to measured throughput): a loss delta here
                # conflates convergence with speed — skip, visibly
                rows.append((metric, vo, vn, None, tols[metric],
                             'skipped (trained %d vs %d steps)'
                             % (so, sn)))
                continue
        if vo:
            delta = (vn - vo) / vo * 100.0
        else:
            # a 0 baseline (e.g. a stateless optimizer's opt-state
            # bytes): any nonzero appearance is an infinite rise, not
            # a silent 0% delta
            delta = float('inf') if vn > 0 else 0.0
        bad = delta * _DIRECTION[metric] > tols[metric]
        rows.append((metric, vo, vn, delta, tols[metric],
                     'REGRESSION' if bad else 'ok'))
    return rows


def _fmt_v(v):
    if v is None:
        return '-'
    if abs(v) >= 1e6:
        return '%.3e' % v
    return ('%.4f' % v).rstrip('0').rstrip('.')


def render(rows, old_path, new_path):
    lines = ['bench diff: %s -> %s' % (old_path, new_path),
             '  %-26s %14s %14s %9s %7s  %s'
             % ('metric', 'old', 'new', 'delta%', 'tol%', 'verdict')]
    for metric, vo, vn, delta, tol, verdict in rows:
        lines.append('  %-26s %14s %14s %9s %7s  %s'
                     % (metric, _fmt_v(vo), _fmt_v(vn),
                        '-' if delta is None else '%+.1f' % delta,
                        '%.1f' % tol, verdict))
    return '\n'.join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='Compare two BENCH_r*.json artifacts (throughput, '
                    'MFU, XLA temp bytes, per-device opt-state bytes, '
                    'cold compile time) with per-metric tolerance; '
                    'non-zero exit on regression — the post-bench CI '
                    'gate (docs/perf.md).')
    ap.add_argument('old', help='baseline bench artifact')
    ap.add_argument('new', help='candidate bench artifact')
    ap.add_argument('--tol-pct', type=float, default=None,
                    help='one tolerance (%%) for every metric '
                         '(default: per-metric — throughput/mfu/temp '
                         '5%%, opt-state bytes 10%%, compile 25%%)')
    ap.add_argument('--tol', action='append', default=[],
                    metavar='METRIC=PCT',
                    help='per-metric tolerance override, e.g. '
                         '--tol mfu=2 (repeatable)')
    ap.add_argument('--strict', action='store_true',
                    help='exit 3 when the runs are not '
                         'config-comparable instead of 0')
    args = ap.parse_args(argv)
    tols = dict(_DEF_TOL)
    if args.tol_pct is not None:
        tols = {k: args.tol_pct for k in tols}
    for spec in args.tol:
        name, _, pct = spec.partition('=')
        if name not in tols or not pct:
            ap.error('unknown --tol %r (metrics: %s)'
                     % (spec, ', '.join(sorted(tols))))
        tols[name] = float(pct)
    old, new = load_bench(args.old), load_bench(args.new)
    reasons = comparability(old, new)
    if reasons:
        print('bench_diff: runs are not config-comparable — %s'
              % '; '.join(reasons))
        print('(a CPU-fallback or re-configured round; no regression '
              'verdict is claimable)')
        return 3 if args.strict else 0
    rows = diff(old, new, tols)
    print(render(rows, args.old, args.new))
    skipped = [r for r in rows if r[5].startswith('skipped')]
    if skipped:
        # a skip is visible evidence, never a silent pass: say exactly
        # which metrics went ungated this round and why
        print('note: ungated this round — %s'
              % '; '.join('%s %s' % (r[0], r[5][len('skipped '):])
                          for r in skipped))
    bad = [r for r in rows if r[5].startswith('REGRESSION')]
    if bad:
        print('REGRESSION: %s' % ', '.join(r[0] for r in bad))
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
