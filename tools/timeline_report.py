#!/usr/bin/env python
"""Render the step-timeline critical-path block from a telemetry JSONL
log, offline.

A run with ``MXTPU_TELEMETRY=1 MXTPU_TIMELINE=1`` appends a
``timeline`` record per sync round (process 0) and folds the final one
into the ``summary`` record — the gang step decomposed into compute /
collective-wait / io / host-side per host, with the gating host and
phase named. This tool re-renders it without re-running anything::

    python tools/timeline_report.py telemetry.jsonl
    python tools/timeline_report.py /mnt/run1/logs   # gang log dir

Uses the SAME renderer as the live end-of-run summary
(mxnet_tpu/telemetry/export.py::_timeline_lines), so the offline block
is byte-identical to the one the run logged — the round-trip the
timeline tests pin. ``--json`` dumps the raw attribution dict instead
(for scripting: jq over per_host/critical_phase). Multiple records
keep the LAST one — the end-of-run view — unless ``--all`` lists every
one with its timestamp, which reads as a per-round phase table: how
the critical path moved over the run.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from mxnet_tpu.telemetry.export import _timeline_lines  # noqa: E402
from telemetry_report import expand_paths, load  # noqa: E402


def timeline_records(records):
    """Every timeline attribution dict in a parsed record list, oldest
    first: the dedicated ``timeline`` records, plus any ``summary``
    record's ``timeline`` key (a crashed run may have either)."""
    out = []
    for r in records:
        if r.get('type') == 'timeline':
            out.append((r.get('t'), {k: v for k, v in r.items()
                                     if k not in ('type', 't', 'host')}))
        elif r.get('type') == 'summary' and r.get('timeline'):
            out.append((r.get('t'), r['timeline']))
    return out


def render(tl):
    """One attribution dict -> the summary-table block, as a string."""
    return '\n'.join(_timeline_lines(tl))


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='Render the step-timeline block (per-host compute/'
                    'collective/io/host-side decomposition, clock '
                    'offsets, gating host and phase, skew) from a '
                    'telemetry JSONL log, offline — byte-identical to '
                    'the block the live summary table logged.')
    ap.add_argument('paths', nargs='+',
                    help='telemetry JSONL file(s) to render, or a gang '
                         'log directory holding h<i>.jsonl files')
    ap.add_argument('--json', action='store_true',
                    help='dump the raw attribution dict(s) as JSON '
                         'instead of the rendered block')
    ap.add_argument('--all', action='store_true',
                    help='render every timeline record in the log(s) — '
                         'the per-round phase table — not just the last')
    args = ap.parse_args(argv)
    records = []
    for p in expand_paths(args.paths):
        records.extend(load(p))
    records.sort(key=lambda r: r.get('t') or 0.0)
    recs = timeline_records(records)
    if not recs:
        sys.stderr.write(
            'timeline_report: %s hold(s) no timeline record — was the '
            'run started with MXTPU_TELEMETRY=1 MXTPU_TIMELINE=1?\n'
            % ', '.join(args.paths))
        return 1
    picked = recs if args.all else recs[-1:]
    if args.json:
        dicts = [r for _t, r in picked]
        print(json.dumps(dicts[0] if len(dicts) == 1 else dicts,
                         indent=2))
        return 0
    blocks = []
    for t, tl in picked:
        if args.all and t is not None:
            blocks.append('== t=%s ==' % t)
        blocks.append(render(tl))
    print('\n'.join(blocks))
    return 0


if __name__ == '__main__':
    sys.exit(main())
