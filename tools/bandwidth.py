#!/usr/bin/env python3
"""Collective-bandwidth measurement tool.

Reference: tools/bandwidth/measure.py (times kvstore push+pull of
ResNet/VGG-sized parameter sets across devices and reports GB/s).

TPU-native: the data plane is XLA collectives over the device mesh, so
this measures what actually carries gradients here — psum (allreduce),
all_gather and reduce_scatter over a 1-D mesh axis — plus the
kvstore-level push+pull round for parity with the reference's number.

    python tools/bandwidth.py --sizes 1e6,1e7 --iters 20
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure_collectives(sizes, iters, dtype='float32'):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(devs, ('x',))
    results = []
    for size in sizes:
        size = int(size)
        # per-shard blocks must themselves split n ways (psum_scatter)
        per_dev = max(size // (n * n), 1) * n
        x = jnp.ones((n * per_dev,), dtype=dtype)

        def allreduce(v):
            return jax.lax.psum(v, 'x')

        def allgather(v):
            return jax.lax.all_gather(v, 'x', tiled=True)

        def reducescatter(v):
            return jax.lax.psum_scatter(v, 'x', tiled=True)

        cases = {
            # bus bytes factors per the standard ring-collective cost model
            'psum': (shard_map(allreduce, mesh=mesh, in_specs=P('x'),
                               out_specs=P('x')), 2 * (n - 1) / n),
            'all_gather': (shard_map(allgather, mesh=mesh, in_specs=P('x'),
                                     out_specs=P(), check_rep=False),
                           (n - 1) / n),
            'reduce_scatter': (shard_map(reducescatter, mesh=mesh,
                                         in_specs=P('x'), out_specs=P('x')),
                               (n - 1) / n),
        }
        nbytes = x.size * x.dtype.itemsize
        for name, (fn, bus_factor) in cases.items():
            jfn = jax.jit(fn)
            jfn(x).block_until_ready()  # compile
            t0 = time.perf_counter()
            for _ in range(iters):
                out = jfn(x)
            out.block_until_ready()
            dt = (time.perf_counter() - t0) / iters
            gbps = nbytes * bus_factor / dt / 1e9
            results.append({'op': name, 'bytes': nbytes, 'time_ms': dt * 1e3,
                            'busbw_GBps': gbps})
            print('%-15s %10d B  %8.3f ms  %8.2f GB/s (bus)' %
                  (name, nbytes, dt * 1e3, gbps))
    return results


def measure_kvstore(sizes, iters, kv_type='device', label='kv_push_pull'):
    """Reference measure.py's actual protocol: init + timed push/pull."""
    import numpy as np
    import mxnet_tpu as mx

    kv = mx.kv.create(kv_type)
    results = []
    for size in sizes:
        size = int(size)
        arr = mx.nd.array(np.ones(size, np.float32))
        out = mx.nd.zeros((size,))
        kv.init(0, arr)
        kv.push(0, arr)
        kv.pull(0, out=out)
        out.wait_to_read()
        t0 = time.perf_counter()
        for _ in range(iters):
            kv.push(0, arr)
            kv.pull(0, out=out)
        out.wait_to_read()
        dt = (time.perf_counter() - t0) / iters
        gbps = size * 4 * 2 / dt / 1e9  # push + pull
        results.append({'op': label, 'bytes': size * 4,
                        'time_ms': dt * 1e3, 'GBps': gbps})
        print('%-15s %10d B  %8.3f ms  %8.2f GB/s' %
              (label, size * 4, dt * 1e3, gbps))
    return results


def measure_dist(sizes, iters, n_servers=1, timeout_s=600):
    """PS-tier bandwidth: spawn a real 1-worker/N-server TCP cluster via
    tools/launch.py and time dist_sync push+pull (the reference
    measure.py against its parameter servers). The cluster runs in its
    own process group so a wedged server can be killed wholesale; the
    worker's printed rows are parsed back into result dicts."""
    import signal
    import subprocess
    env = dict(os.environ)
    env.pop('DMLC_ROLE', None)
    env['JAX_PLATFORMS'] = 'cpu'
    env.pop('XLA_FLAGS', None)
    here = os.path.abspath(__file__)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(os.path.dirname(here), 'launch.py'),
         '-n', '1', '-s', str(n_servers), sys.executable, here,
         '--dist-worker', '--sizes', ','.join(str(int(s)) for s in sizes),
         '--iters', str(iters)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, start_new_session=True)
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        # kill the WHOLE group: orphaned scheduler/server processes hold
        # the inherited pipes open and would hang a plain kill+communicate
        os.killpg(proc.pid, signal.SIGKILL)
        out, err = proc.communicate()
        sys.stderr.write((err or '')[-3000:])
        raise SystemExit('dist bandwidth run timed out')
    sys.stdout.write(out)
    if proc.returncode != 0:
        sys.stderr.write((err or '')[-3000:])
        raise SystemExit('dist bandwidth run failed')
    results = []
    for line in out.splitlines():
        parts = line.split()
        if len(parts) == 7 and parts[0] == 'dist_push_pull':
            results.append({'op': parts[0], 'bytes': int(parts[1]),
                            'time_ms': float(parts[3]),
                            'GBps': float(parts[5])})
    if not results:
        # a format drift in measure_kvstore's print must not silently
        # drop the dist tier from the report
        raise SystemExit('no dist rows parsed from worker output:\n'
                         + out[-2000:])
    return results


def measure_dist_worker(sizes, iters):
    return measure_kvstore(sizes, iters, kv_type='dist_sync',
                           label='dist_push_pull')


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument('--sizes', default='1e6,1e7',
                   help='comma-separated element counts')
    p.add_argument('--iters', type=int, default=20)
    p.add_argument('--dtype', default='float32',
                   choices=['float32', 'bfloat16'])
    p.add_argument('--kvstore', action='store_true',
                   help='also time kvstore push+pull (reference protocol)')
    p.add_argument('--dist', action='store_true',
                   help='also time the TCP parameter-server tier '
                        '(spawns a local 1-worker/1-server cluster)')
    p.add_argument('--dist-worker', action='store_true',
                   help=argparse.SUPPRESS)
    p.add_argument('--cpu-devices', type=int, default=0,
                   help='force an N-device virtual CPU mesh (the container '
                        'pre-pins jax to the TPU backend; env vars alone '
                        'are too late)')
    args = p.parse_args(argv)
    sizes = [float(s) for s in args.sizes.split(',')]
    if args.dist_worker:
        import jax
        jax.config.update('jax_platforms', 'cpu')
        return measure_dist_worker(sizes, args.iters)
    if args.cpu_devices:
        os.environ['XLA_FLAGS'] = (
            os.environ.get('XLA_FLAGS', '') +
            ' --xla_force_host_platform_device_count=%d' % args.cpu_devices)
        import jax
        jax.config.update('jax_platforms', 'cpu')
    import jax
    print('devices: %d x %s' % (len(jax.devices()),
                                jax.devices()[0].device_kind))
    results = measure_collectives(sizes, args.iters, args.dtype)
    if args.kvstore:
        results += measure_kvstore(sizes, args.iters)
    if args.dist:
        results += measure_dist(sizes, args.iters)
    return results


if __name__ == '__main__':
    main()
