#!/usr/bin/env python
"""Gang-scheduled supervision of a real multi-host training job.

``tools/train_supervisor.py`` relaunches ONE dying process. A
multi-host job is W processes in one ``jax.distributed`` gang, and it
dies as a unit: when one worker exits unclean — the hang watchdog's
abort (85), a host loss (113), an OOM kill, a segfault — the survivors
are wedged inside DCN collectives that can never complete. No
per-process restart can help them; the whole gang must be torn down
and relaunched. This tool is that tier::

    python tools/gang_supervisor.py -n 4 -- python train.py
    MXTPU_RESTART_MAX=5 python tools/gang_supervisor.py -n 4 \
        --elastic-min-hosts 2 --log-dir /mnt/run1/logs -- python train.py

Per attempt it launches W workers with the same env protocol
``tools/launch.py`` speaks — ``MXTPU_COORDINATOR`` (a FRESH port every
attempt: the previous coordinator's socket may linger, and on jax
0.4.x a coordinator bind conflict is unrecoverable in-process),
``MXTPU_NUM_HOSTS``, ``MXTPU_HOST_ID`` — prefixes each worker's output
``[h<i>]``, and supervises them as a GANG:

- ANY worker exiting unclean tears the rest down (SIGTERM, a grace
  period, SIGKILL) and relaunches the whole gang against the shared
  restart budget (MXTPU_RESTART_MAX / MXTPU_RESTART_BACKOFF). Worker 0
  IS the coordinator, so coordinator loss is just the i=0 case of the
  same path.
- the liveness tier (--liveness / MXTPU_SUPERVISOR_LIVENESS) watches
  every worker's telemetry JSONL; one wedged worker (no growth past
  the threshold) fails the gang the same way.
- ``--elastic-min-hosts M`` (MXTPU_GANG_MIN_HOSTS): a relaunch
  triggered by a host-loss exit (code 113) proceeds with one FEWER
  worker while more than M remain — the relaunched job sees the
  smaller MXTPU_NUM_HOSTS, ``io.auto_shard`` re-derives every shard
  range, and the checkpoint restore reshards onto the smaller mesh
  (reshard-on-restore, docs/reliability.md). Other failure kinds
  relaunch at full width: a watchdog abort or an OOM kill says nothing
  about the HOST being gone.
- restart-from-last-good rides the children's own MXTPU_CKPT_RESUME
  path, restoring the cross-host-AGREED ``last_good.step`` — the gang
  checkpoint tier guarantees every host certified it, so a relaunch
  can never restore divergent steps.

With ``--log-dir`` (or MXTPU_TELEMETRY_PATH set) worker i writes its
telemetry to ``<dir>/h<i>.jsonl`` and gang restart records append to
``<dir>/gang.jsonl`` — exactly the layout
``python tools/telemetry_report.py <dir>`` globs into the per-host
comparison.

Exit code: 0 when every worker of the final attempt exits clean;
otherwise the FIRST failing worker's code (the root cause — survivors
die of follow-on errors), with the train_supervisor conventions kept:
a liveness kill whose child exited 0 reports 1, CLI misuse (2) never
retries. Budget/backoff/liveness/record code is shared with
tools/train_supervisor.py.
"""
import argparse
import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import launch as _launch                    # noqa: E402
import train_supervisor as _sup             # noqa: E402

HOST_LOSS_EXIT = 113   # mirrored from mxnet_tpu/faults.py (no framework
                       # import here, same rule as train_supervisor)
_POLL_S = 0.1


def _reserve_coord_port(exclude):
    """(socket, port): a reserved coordinator port not in ``exclude``
    (every attempt gets a port no previous attempt of this gang used —
    a dying predecessor cannot alias a fresh gang's rendezvous). The
    reserving socket stays OPEN until immediately before worker 0
    spawns: on jax 0.4.x a coordinator bind conflict dies in grpc
    before Python can catch it, so the widest pick-to-bind window in
    the codebase — W forks plus worker 0's jax import — must not leave
    the port up for grabs."""
    sock, port = _launch._reserve_port()
    for _ in range(64):
        if port not in exclude:
            break
        sock.close()
        sock, port = _launch._reserve_port()
    return sock, port


def _worker_env(base, idx, hosts, port, log_dir):
    env = dict(base)
    env['MXTPU_COORDINATOR'] = '127.0.0.1:%d' % port
    env['MXTPU_NUM_HOSTS'] = str(hosts)
    env['MXTPU_HOST_ID'] = str(idx)
    # workers orphaned by a dead coordinator must fail fast so the
    # gang can be torn down and relaunched on a fresh port — jax's own
    # join default is 5 minutes. An operator's explicit setting wins
    env.setdefault('MXTPU_COORD_TIMEOUT', '60')
    if log_dir:
        env['MXTPU_TELEMETRY_PATH'] = os.path.join(log_dir,
                                                   'h%d.jsonl' % idx)
    return env


class _Liveness:
    """Per-worker stall watches over the h<i>.jsonl files: the
    single-child liveness rule (train_supervisor.FileStallWatch — ONE
    policy for both supervision tiers), applied per gang member."""

    def __init__(self, paths, secs):
        self.secs = secs
        self.watches = [_sup.FileStallWatch(p, secs) for p in paths]

    def stalled(self, alive=None):
        """Index of the first LIVE worker past the stall threshold, or
        None. ``alive`` masks workers that already exited — a finished
        worker's naturally-stale file must not shadow the stall check
        of the still-wedged workers after it."""
        if not self.secs:
            return None
        for i, watch in enumerate(self.watches):
            if alive is not None and not alive[i]:
                continue
            if watch.stalled() is not None:
                return i
        return None


def _teardown(workers, grace=_sup._TERM_GRACE_S):
    """SIGTERM every live worker, one shared grace period, SIGKILL the
    rest. The survivors are wedged inside collectives that can never
    complete — there is nothing to wait for past the grace."""
    for p in workers:
        if p.poll() is None:
            p.terminate()
    deadline = time.time() + grace
    for p in workers:
        if p.poll() is None:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                pass
    for p in workers:
        if p.poll() is None:
            p.kill()
            p.wait()
    # every worker is dead: drain the [h<i>] pumps so the buffered
    # tail of the failure (the root-cause traceback) reaches the
    # supervisor's streams before any record/return
    _launch.join_pumps(workers)


def _wait_gang(workers, liveness):
    """Block until the gang resolves. Returns ``(failed_idx, code,
    timed_out)``: (None, 0, False) = every worker exited clean;
    otherwise the FIRST unclean exit in completion order, or the first
    liveness stall (code None until the kill)."""
    while True:
        alive = []
        for i, p in enumerate(workers):
            code = p.poll()
            alive.append(code is None)
            if code is not None and code != 0:
                return i, code, False
        if not any(alive):
            return None, 0, False
        i = liveness.stalled(alive=alive)
        if i is not None:
            return i, None, True
        time.sleep(_POLL_S)


def run_gang(cmd, hosts, restart_max, backoff, log_path, log_dir,
             liveness=0.0, min_hosts=0, quiet=False):
    """Supervise ``cmd`` as a ``hosts``-worker gang; returns the final
    exit code (train_supervisor conventions)."""
    attempts = 0
    used_ports = set()
    base_env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_env['PYTHONPATH'] = (repo + os.pathsep + base_env['PYTHONPATH']
                              if base_env.get('PYTHONPATH') else repo)
    # cumulative lost-work seconds across gang relaunches
    # (train_supervisor's accounting, priced once per gang attempt —
    # the gang dies as a unit); every relaunched worker reads it back
    # as MXTPU_GOODPUT_LOST_S and reports prior_lost_s in its goodput
    # record
    lost_total = _sup._env_float('MXTPU_GOODPUT_LOST_S', 0.0)
    while True:
        base_env['MXTPU_GOODPUT_LOST_S'] = '%.3f' % lost_total
        coord_sock, port = _reserve_coord_port(used_ports)
        used_ports.add(port)
        t0 = time.time()
        workers = []
        try:
            envs = [_worker_env(base_env, i, hosts, port, log_dir)
                    for i in range(hosts)]
            # worker 0 (spawned first) binds the coordinator: release
            # the reservation at the last possible moment
            coord_sock.close()
            for i in range(hosts):
                workers.append(_launch.start_worker(cmd, envs[i], i))
        except OSError as e:
            print('gang_supervisor: cannot launch %r (%s)' % (cmd[0], e),
                  file=sys.stderr)
            _teardown(workers)
            return 127
        watch = _Liveness([os.path.join(log_dir, 'h%d.jsonl' % i)
                           for i in range(hosts)] if log_dir else [],
                          liveness)
        try:
            idx, code, timed_out = _wait_gang(workers, watch)
        except KeyboardInterrupt:
            # operator stop: forward and leave — never a fault to retry
            for p in workers:
                if p.poll() is None:
                    p.send_signal(signal.SIGINT)
            _teardown(workers, grace=30.0)
            code = max((p.returncode or 0) for p in workers)
            _sup._record(log_path, {
                'type': 'restart', 'attempt': attempts, 'final': True,
                'reason': 'KeyboardInterrupt', 'exit_code': code,
                'host': 0, 'hosts': hosts})
            return code
        elapsed = time.time() - t0
        if idx is None:
            _launch.join_pumps(workers)   # all exited clean: drain tails
            if attempts and not quiet:
                print('gang_supervisor: gang completed after %d '
                      'restart(s)' % attempts, file=sys.stderr)
            _sup._record(log_path, {
                'type': 'restart', 'attempt': attempts, 'final': True,
                'reason': 'clean_exit', 'exit_code': 0, 'host': 0,
                'hosts': hosts})
            return 0
        if timed_out and not quiet:
            print('gang_supervisor: worker %d wrote no telemetry records '
                  'for %.0fs (liveness %.0fs) — killing the wedged gang'
                  % (idx, liveness, liveness), file=sys.stderr)
        if timed_out:
            code = _sup._kill_child(workers[idx])
        # one worker down (or wedged): the rest are hostages of
        # collectives that cannot complete — take the gang down as a
        # unit before deciding anything else
        _teardown(workers)
        no_retry = (code in _sup._NO_RETRY_CODES and not timed_out)
        if no_retry or attempts >= restart_max:
            _sup._record(log_path, {
                'type': 'restart', 'attempt': attempts, 'final': True,
                'reason': 'usage' if no_retry else 'budget_exhausted',
                'exit_code': code, 'worker': idx, 'host': idx,
                'hosts': hosts})
            if not quiet:
                print('gang_supervisor: giving up after %d attempt(s) '
                      '(worker %d: %s)'
                      % (attempts + 1, idx, _sup._describe(code)),
                      file=sys.stderr)
            # a liveness kill whose SIGTERM handler exited 0 is still
            # an abandoned run (train_supervisor's rule)
            return code if not (timed_out and code == 0) else 1
        attempts += 1
        next_hosts = hosts
        if code == HOST_LOSS_EXIT and min_hosts and hosts > min_hosts:
            # the worker reported its HOST gone (exit 113): relaunch
            # the survivors as a smaller gang. The relaunched job sees
            # the smaller MXTPU_NUM_HOSTS, io.auto_shard re-derives
            # shard coverage, and the restore reshards the agreed
            # last-good checkpoint onto the smaller mesh
            next_hosts = hosts - 1
        delay = _sup.backoff_delay(attempts, backoff)
        lost = _sup.lost_work_secs(elapsed)
        lost_total += lost
        _sup._record(log_path, {
            'type': 'restart', 'attempt': attempts,
            'reason': 'liveness_timeout' if timed_out else 'worker_exit',
            'message': 'worker %d: %s' % (idx, _sup._describe(code)),
            'exit_code': code, 'worker': idx, 'host': idx,
            'hosts': hosts, 'next_hosts': next_hosts,
            'coordinator_port': port,
            'elapsed_s': round(elapsed, 1),
            'lost_s': round(lost, 1),
            'lost_total_s': round(lost_total, 1),
            'backoff_s': delay})
        if not quiet:
            print('gang_supervisor: attempt %d/%d — worker %d died '
                  '(%s after %.0fs); relaunching %d worker(s) on a '
                  'fresh coordinator port in %.1fs'
                  % (attempts, restart_max, idx, _sup._describe(code),
                     elapsed, next_hosts, delay), file=sys.stderr)
        hosts = next_hosts
        if delay:
            try:
                time.sleep(delay)
            except KeyboardInterrupt:
                _sup._record(log_path, {
                    'type': 'restart', 'attempt': attempts, 'final': True,
                    'reason': 'KeyboardInterrupt', 'exit_code': code,
                    'host': 0, 'hosts': hosts})
                return code


def main(argv=None):
    p = argparse.ArgumentParser(
        description='Launch W workers as one jax.distributed gang and '
                    'supervise them as a unit: any unclean worker exit '
                    'tears the gang down and relaunches it on a fresh '
                    'coordinator port against the MXTPU_RESTART_* '
                    'budget.')
    p.add_argument('-n', '--num-hosts', type=int, required=True,
                   help='worker (process) count of the gang')
    p.add_argument('--restart-max', type=int, default=None,
                   help='restart budget (default: MXTPU_RESTART_MAX or 3)')
    p.add_argument('--backoff', type=float, default=None,
                   help='base backoff seconds '
                        '(default: MXTPU_RESTART_BACKOFF or 2)')
    p.add_argument('--elastic-min-hosts', type=int, default=None,
                   help='relaunch a host-loss (exit 113) with one fewer '
                        'worker while more than this many remain '
                        '(default: MXTPU_GANG_MIN_HOSTS or 0 = never '
                        'shrink)')
    p.add_argument('--log-dir', default=None,
                   help="per-worker telemetry JSONLs land here as "
                        "h<i>.jsonl and restart records as gang.jsonl "
                        "(default: the directory of MXTPU_TELEMETRY_PATH "
                        "when set)")
    p.add_argument('--log', default=None,
                   help='JSONL file for gang restart records (default: '
                        '<log-dir>/gang.jsonl)')
    p.add_argument('--liveness', type=float, default=None,
                   help='kill + relaunch the gang when any worker\'s '
                        'telemetry JSONL stops growing for this many '
                        'seconds (default: MXTPU_SUPERVISOR_LIVENESS or '
                        '0 = off; needs MXTPU_TELEMETRY=1 in the '
                        'children and a --log-dir)')
    p.add_argument('--quiet', action='store_true',
                   help='suppress supervisor stderr chatter')
    p.add_argument('cmd', nargs=argparse.REMAINDER,
                   help='training command (prefix with -- )')
    args = p.parse_args(argv)
    cmd = list(args.cmd)
    if cmd and cmd[0] == '--':
        cmd = cmd[1:]
    if not cmd:
        p.error('no training command given (append: -- python train.py ...)')
    if args.num_hosts < 1:
        p.error('-n must be >= 1')
    restart_max = args.restart_max if args.restart_max is not None \
        else _sup._env_int('MXTPU_RESTART_MAX', 3)
    backoff = args.backoff if args.backoff is not None \
        else _sup._env_float('MXTPU_RESTART_BACKOFF', 2.0)
    min_hosts = args.elastic_min_hosts if args.elastic_min_hosts is not None \
        else _sup._env_int('MXTPU_GANG_MIN_HOSTS', 0)
    liveness = args.liveness if args.liveness is not None \
        else _sup._env_float('MXTPU_SUPERVISOR_LIVENESS', 0.0)
    log_dir = args.log_dir
    if log_dir is None and os.environ.get('MXTPU_TELEMETRY_PATH'):
        log_dir = os.path.dirname(os.path.abspath(
            os.environ['MXTPU_TELEMETRY_PATH']))
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    log_path = args.log or (os.path.join(log_dir, 'gang.jsonl')
                            if log_dir else None)
    if liveness > 0 and not log_dir:
        print('gang_supervisor: --liveness needs a --log-dir (or '
              'MXTPU_TELEMETRY_PATH) so per-worker h<i>.jsonl files '
              'exist to watch — liveness disabled', file=sys.stderr)
        liveness = 0.0
    if not args.quiet and not os.environ.get('MXTPU_CKPT_DIR'):
        print('gang_supervisor: MXTPU_CKPT_DIR is not set — gang '
              'relaunches will rerun from step 0 (set MXTPU_CKPT_DIR '
              'and MXTPU_CKPT_EVERY so relaunches resume from the '
              'cross-host-agreed last-good checkpoint)', file=sys.stderr)
    return run_gang(cmd, args.num_hosts, restart_max, backoff, log_path,
                    log_dir, liveness=liveness, min_hosts=min_hosts,
                    quiet=args.quiet)


if __name__ == '__main__':
    sys.exit(main())
