#!/usr/bin/env python
"""Render the memory attribution block from a telemetry JSONL log,
offline.

A run with ``MXTPU_TELEMETRY=1 MXTPU_MEMORY=1`` appends ``memory``
records (and folds the end-of-run dict into the ``summary`` record)
carrying the per-layer HBM attribution, the live-bytes timeline tail
and the steps-to-OOM forecast. This tool re-renders it without
re-running anything::

    python tools/memory_report.py telemetry.jsonl

Uses the SAME renderer as the live end-of-run summary
(mxnet_tpu/telemetry/export.py::_memory_lines), so the offline block
is byte-identical to the one the run logged — the round-trip the
memory tests pin. ``--json`` dumps the raw analysis dict instead.
Multiple records keep the LAST full one (the end-of-run view) unless
``--all`` lists every one with its timestamp.

``--what-if`` appends a capacity-planning table: holding the program's
argument bytes (weights/optimizer state) fixed and scaling the
activation footprint (temp + out - alias) linearly, it lists the
projected device bytes at several multiples of the current batch or
window and the largest multiple that still fits ``bytes_limit``.
Pass ``--batch N`` (the run's global batch or decode window) to label
the rows in concrete batch sizes instead of bare multiples.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from mxnet_tpu.telemetry.export import _memory_lines  # noqa: E402
from telemetry_report import load  # noqa: E402  (same loader conventions)


def memory_records(records):
    """Every memory analysis dict in a parsed record list, oldest
    first: the dedicated ``memory`` records, plus any ``summary``
    record's ``memory`` key (a crashed run may have either). Summary
    folds sort after same-log timeline samples so the end-of-run view
    (which carries the per-layer table) wins the default pick."""
    out = []
    for r in records:
        if r.get('type') == 'memory':
            out.append((r.get('t'), {k: v for k, v in r.items()
                                     if k not in ('type', 't', 'host')}))
        elif r.get('type') == 'summary' and r.get('memory'):
            out.append((r.get('t'), r['memory']))
    return out


def render(mem):
    """One analysis dict -> the summary-table block, as a string."""
    return '\n'.join(_memory_lines(mem))


def what_if_lines(mem, batch=None):
    """Capacity planning from one analysis dict: args bytes are fixed
    (weights + optimizer state survive any batch), activations
    (temp + out - alias) scale linearly with batch/window, so the
    largest multiple that fits is k = (limit - args) / activations."""
    args_b = int(mem.get('args_bytes') or 0)
    act = (int(mem.get('temp_bytes') or 0) + int(mem.get('output_bytes')
           or 0) - int(mem.get('alias_bytes') or 0))
    limit = mem.get('bytes_limit')
    lines = ['-- what-if: batch/window scaling --']
    if act <= 0 or not limit:
        lines.append('  (needs a compiled-program analysis and a '
                     'device bytes_limit; re-run with MXTPU_MEMORY=1 '
                     'on an accelerator)')
        return lines
    limit = int(limit)
    k_max = max(0.0, (limit - args_b) / float(act))
    mib = 2.0 ** 20
    unit = 'batch' if batch else 'scale'
    lines.append('  %-10s %12s %12s  %s'
                 % (unit, 'projected_MiB', 'limit_MiB', 'fits'))
    mults = [0.5, 1.0, 2.0, 4.0]
    if k_max > 0 and all(abs(k_max - m) > 1e-9 for m in mults):
        mults = sorted(mults + [k_max])
    for k in mults:
        proj = args_b + k * act
        label = ('%d' % round(k * batch)) if batch else ('%.2fx' % k)
        lines.append('  %-10s %12.1f %12.1f  %s'
                     % (label, proj / mib, limit / mib,
                        'yes' if proj <= limit else 'OOM'))
    if batch:
        lines.append('  largest %s that fits: %d (%.2fx of current)'
                     % (unit, int(k_max * batch), k_max))
    else:
        lines.append('  largest scale that fits: %.2fx' % k_max)
    return lines


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='Render the memory attribution block (per-layer '
                    'args/temp/out/alias byte shares calibrated to '
                    "XLA's memory_analysis totals, live-bytes tail, "
                    'headroom and steps-to-OOM forecast) from a '
                    'telemetry JSONL log, offline — byte-identical to '
                    'the block the live summary table logged.')
    ap.add_argument('path', help='telemetry JSONL file to render')
    ap.add_argument('--json', action='store_true',
                    help='dump the raw analysis dict(s) as JSON instead '
                         'of the rendered block')
    ap.add_argument('--all', action='store_true',
                    help='render every memory record in the log, not '
                         'just the last')
    ap.add_argument('--what-if', action='store_true',
                    help='append a capacity-planning table: projected '
                         'device bytes at several activation-scale '
                         'multiples and the largest that fits')
    ap.add_argument('--batch', type=int, default=None,
                    help='current global batch (or decode window) — '
                         'labels the what-if rows in concrete sizes')
    args = ap.parse_args(argv)
    recs = memory_records(load(args.path))
    if not recs:
        sys.stderr.write(
            'memory_report: %s holds no memory record — was the run '
            'started with MXTPU_TELEMETRY=1 MXTPU_MEMORY=1?\n'
            % args.path)
        return 1
    picked = recs if args.all else recs[-1:]
    if args.json:
        dicts = [r for _t, r in picked]
        print(json.dumps(dicts[0] if len(dicts) == 1 else dicts,
                         indent=2))
        return 0
    blocks = []
    for t, mem in picked:
        if args.all and t is not None:
            blocks.append('== t=%s ==' % t)
        blocks.append(render(mem))
    if args.what_if:
        blocks.append('\n'.join(what_if_lines(picked[-1][1],
                                              batch=args.batch)))
    print('\n'.join(blocks))
    return 0


if __name__ == '__main__':
    sys.exit(main())
