#!/usr/bin/env python3
"""Parse training logs into a per-epoch table (reference
tools/parse_log.py — same job over this framework's fit() log lines:
``Epoch[N] Train-<metric>=V``, ``Epoch[N] Validation-<metric>=V``,
``Epoch[N] Time cost=T``).

    python tools/parse_log.py train.log [--format markdown|csv]
"""
import argparse
import re
import sys

_TRAIN = re.compile(r'Epoch\[(\d+)\] Train-([^=\s]+)=([\d.eE+-]+)')
_VAL = re.compile(r'Epoch\[(\d+)\] Validation-([^=\s]+)=([\d.eE+-]+)')
_TIME = re.compile(r'Epoch\[(\d+)\] Time cost=([\d.eE+-]+)')


def parse(lines):
    """Returns (rows, metric_names): one row dict per epoch."""
    epochs = {}

    def row(i):
        return epochs.setdefault(int(i), {'epoch': int(i)})

    metrics = []
    for line in lines:
        m = _TRAIN.search(line)
        if m:
            key = 'train-' + m.group(2)
            row(m.group(1))[key] = float(m.group(3))
            if key not in metrics:
                metrics.append(key)
            continue
        m = _VAL.search(line)
        if m:
            key = 'val-' + m.group(2)
            row(m.group(1))[key] = float(m.group(3))
            if key not in metrics:
                metrics.append(key)
            continue
        m = _TIME.search(line)
        if m:
            row(m.group(1))['time'] = float(m.group(2))
            if 'time' not in metrics:
                metrics.append('time')
    return [epochs[k] for k in sorted(epochs)], metrics


def render(rows, metrics, fmt='markdown'):
    cols = ['epoch'] + metrics
    out = []
    if fmt == 'markdown':
        out.append('| ' + ' | '.join(cols) + ' |')
        out.append('|' + '---|' * len(cols))
        for r in rows:
            out.append('| ' + ' | '.join(
                ('%g' % r[c]) if c in r else '-' for c in cols) + ' |')
    else:
        out.append(','.join(cols))
        for r in rows:
            out.append(','.join(('%g' % r[c]) if c in r else '' for c in cols))
    return '\n'.join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('logfile')
    ap.add_argument('--format', choices=['markdown', 'csv'],
                    default='markdown')
    args = ap.parse_args(argv)
    with open(args.logfile) as f:
        rows, metrics = parse(f)
    print(render(rows, metrics, args.format))
    return 0


if __name__ == '__main__':
    sys.exit(main())
