#!/usr/bin/env python
"""Stitch a gang run's per-host logs into ONE offset-corrected
Perfetto trace.

Each host of a pod run writes its own telemetry JSONL (``h<i>.jsonl``
under the gang log directory) and, when profiling is on, its own
chrome trace — all stamped with that host's LOCAL clock. This tool
merges them into a single chrome-trace JSON that Perfetto (or
chrome://tracing) opens as one timeline, with one process row per host
(``pid`` = host index) and every timestamp shifted onto host-median
time using the per-host ``clock_offset_ms`` the timeline plane
estimated (MXTPU_TIMELINE=1 — the LAST ``timeline`` record wins, the
end-of-run view of the clock rings)::

    python tools/trace_merge.py /mnt/run1/logs -o pod.trace.json

Span records in the JSONL logs (every telemetry run has them) become
the trace events; a host's dedicated chrome trace (MXTPU_TRACE_PATH)
can be folded in on top with a repeatable ``--trace HOST=PATH`` — its
events keep their names/durations but are re-stamped ``pid=HOST`` and
shifted by that host's offset, so device lanes and telemetry spans
line up on the same corrected clock.

Without a timeline record the merge still works, with a warning and
zero offsets — the hosts render side by side on their raw clocks.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from telemetry_report import expand_paths, load  # noqa: E402


def clock_offsets(record_lists):
    """{host: offset_ms} from the LAST ``timeline`` record across the
    logs (process 0 publishes them, so one log carries them all).
    Empty when the run never aligned clocks (MXTPU_TIMELINE off)."""
    last = None
    for recs in record_lists:
        for r in recs:
            if r.get('type') == 'timeline' and r.get('per_host'):
                # exit summaries on non-zero ranks are single-host and
                # carry no offsets — only aligned rounds qualify
                if not any(row.get('clock_offset_ms') is not None
                           for row in r['per_host']):
                    continue
                if last is None or (r.get('t') or 0) >= (last.get('t') or 0):
                    last = r
    if last is None:
        return {}
    out = {}
    for row in last['per_host']:
        off = row.get('clock_offset_ms')
        if row.get('host') is not None and off is not None:
            out[int(row['host'])] = float(off)
    return out


def span_events(record_lists, offsets):
    """Chrome trace events built from the JSONL ``span`` records, one
    process row per host, timestamps shifted onto the aligned clock
    (chrome 'ts' is microseconds; a span record's 't' is the epoch
    stamp of the span's START — telemetry._Span emits t0)."""
    events = []
    for i, recs in enumerate(record_lists):
        for r in recs:
            if r.get('type') != 'span':
                continue
            t = r.get('t')
            dur = r.get('dur_ms')
            if not isinstance(t, (int, float)) \
                    or not isinstance(dur, (int, float)):
                continue
            host = int(r.get('host', i))
            off_s = offsets.get(host, 0.0) / 1e3
            events.append({'name': r.get('name', '?'), 'cat': 'span',
                           'ph': 'X', 'ts': (t - off_s) * 1e6,
                           'dur': dur * 1e3, 'pid': host, 'tid': 0})
    return events


def fold_trace(path, host, offsets):
    """Events of one host's dedicated chrome trace, re-stamped onto
    the merged pid space and the aligned clock. Both container shapes
    chrome emits are accepted ({'traceEvents': [...]} and a bare
    list)."""
    with open(path) as f:
        doc = json.load(f)
    raw = doc.get('traceEvents', doc) if isinstance(doc, dict) else doc
    shift_us = offsets.get(host, 0.0) * 1e3
    out = []
    for ev in raw:
        if not isinstance(ev, dict):
            continue
        ev = dict(ev)
        if ev.get('ph') == 'M':
            # metadata rows (process_name etc.) are re-emitted by the
            # merge itself — a second, host-local copy would fight it
            continue
        ev['pid'] = host
        if isinstance(ev.get('ts'), (int, float)):
            ev['ts'] = ev['ts'] - shift_us
        out.append(ev)
    return out


def merge(record_lists, traces=()):
    """The merged chrome-trace document for per-host record lists plus
    optional (host, chrome-trace-path) pairs."""
    offsets = clock_offsets(record_lists)
    events = span_events(record_lists, offsets)
    for host, path in traces:
        events.extend(fold_trace(path, host, offsets))
    hosts = sorted({ev['pid'] for ev in events})
    meta = []
    for host in hosts:
        label = 'host %d' % host
        if host in offsets:
            label += ' (offset %+.3f ms)' % offsets[host]
        meta.append({'name': 'process_name', 'ph': 'M', 'pid': host,
                     'args': {'name': label}})
    events.sort(key=lambda ev: ev.get('ts', 0.0))
    return {'traceEvents': meta + events, 'displayTimeUnit': 'ms'}, offsets


def _parse_trace_arg(spec):
    host, _, path = spec.partition('=')
    try:
        return int(host), path
    except ValueError:
        raise argparse.ArgumentTypeError(
            '--trace wants HOST=PATH (e.g. 0=trace.h0.json), got %r'
            % spec)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='Merge a gang run\'s per-host telemetry logs (and '
                    'optional per-host chrome traces) into one '
                    'offset-corrected Perfetto trace, pid = host.')
    ap.add_argument('paths', nargs='+',
                    help='gang log directory, or the h<i>.jsonl files')
    ap.add_argument('--trace', action='append', default=[],
                    type=_parse_trace_arg, metavar='HOST=PATH',
                    help='fold a host\'s dedicated chrome trace '
                         '(MXTPU_TRACE_PATH) into its process row; '
                         'repeatable')
    ap.add_argument('-o', '--out', default='merged.trace.json',
                    help='output trace file (default: %(default)s)')
    args = ap.parse_args(argv)
    paths = expand_paths(args.paths)
    if not paths:
        sys.stderr.write('trace_merge: nothing to merge\n')
        return 1
    record_lists = [load(p) for p in paths]
    if not any(record_lists):
        sys.stderr.write('trace_merge: %s hold(s) no records\n'
                         % ', '.join(paths))
        return 1
    doc, offsets = merge(record_lists, traces=args.trace)
    n_ev = sum(1 for ev in doc['traceEvents'] if ev.get('ph') != 'M')
    if not n_ev:
        sys.stderr.write('trace_merge: no span events found — was the '
                         'run started with MXTPU_TELEMETRY=1?\n')
        return 1
    if not offsets:
        sys.stderr.write('trace_merge: no timeline record — merging on '
                         'raw host clocks (run with MXTPU_TIMELINE=1 '
                         'for aligned timestamps)\n')
    with open(args.out, 'w') as f:
        json.dump(doc, f)
    hosts = sorted({ev['pid'] for ev in doc['traceEvents']})
    print('trace_merge: %d events from %d host(s) -> %s'
          % (n_ev, len(hosts), args.out))
    return 0


if __name__ == '__main__':
    sys.exit(main())
