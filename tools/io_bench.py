"""ImageRecordIter streaming-scale bench (VERDICT r3 #3 done-criterion).

Generates a synthetic JPEG .rec of the requested size, then streams it
through ImageRecordIter with full augmentation, reporting throughput
(img/s, MB/s) and the resident-set delta — which must stay flat (the
round-3 eager loader was O(dataset) host memory).

    python tools/io_bench.py --gb 2.5 --batch 32 --threads 4

Prints one JSON line. The 'rss_delta_mb' field is the peak RSS growth
between the first and last measurement window; 'passes' asserts it is
bounded by a few batch-queues, not the dataset.
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _rss_mb():
    with open('/proc/self/status') as f:
        for line in f:
            if line.startswith('VmRSS'):
                return int(line.split()[1]) / 1024.0
    return 0.0


def build_rec(path, target_bytes, hw):
    from mxnet_tpu.recordio import MXRecordIO, IRHeader, pack_img
    rng = np.random.RandomState(0)
    rec = MXRecordIO(path, 'w')
    # a handful of distinct JPEGs cycled with distinct headers: real
    # decode work per record without hours of synthesis
    protos = [(rng.rand(hw, hw, 3) * 255).astype(np.uint8)
              for _ in range(64)]
    from mxnet_tpu.recordio import pack  # noqa: F401 (doc pointer)
    payloads = [pack_img(IRHeader(0, float(i % 10), i, 0), protos[i],
                         quality=90, img_fmt='.jpg')
                for i in range(64)]
    n, written = 0, 0
    t0 = time.perf_counter()
    while written < target_bytes:
        rec.write(payloads[n % 64])
        written += len(payloads[n % 64]) + 12
        n += 1
    rec.close()
    print('[io_bench] wrote %d records, %.2f GB in %.1fs'
          % (n, written / 1e9, time.perf_counter() - t0), file=sys.stderr)
    return n, written


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--gb', type=float, default=2.0)
    ap.add_argument('--batch', type=int, default=32)
    ap.add_argument('--threads', type=int, default=4)
    ap.add_argument('--hw', type=int, default=256)
    ap.add_argument('--crop', type=int, default=224)
    ap.add_argument('--path', default='/tmp/io_bench.rec')
    ap.add_argument('--keep', action='store_true')
    args = ap.parse_args()

    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import jax
    jax.config.update('jax_platforms', 'cpu')
    from mxnet_tpu import io as mio

    if not os.path.exists(args.path):
        n, nbytes = build_rec(args.path, args.gb * 1e9, args.hw)
    else:
        nbytes = os.path.getsize(args.path)
        n = None
    rss0 = _rss_mb()
    it = mio.ImageRecordIter(
        path_imgrec=args.path, data_shape=(3, args.crop, args.crop),
        batch_size=args.batch, shuffle=True, rand_crop=True,
        rand_mirror=True, preprocess_threads=args.threads,
        scale=1.0 / 255, mean_r=0.5, mean_g=0.5, mean_b=0.5)
    rss_after_open = _rss_mb()
    imgs = 0
    peak = rss_after_open
    t0 = time.perf_counter()
    for b in it:
        imgs += args.batch
        if imgs % (args.batch * 64) == 0:
            peak = max(peak, _rss_mb())
    dt = time.perf_counter() - t0
    peak = max(peak, _rss_mb())
    out = {
        'metric': 'image_record_stream',
        'value': round(imgs / dt, 1),
        'unit': 'images/sec',
        'mb_per_s': round(nbytes / 1e6 / dt, 1),
        'images': imgs,
        'file_gb': round(nbytes / 1e9, 2),
        'threads': args.threads,
        'rss_open_mb': round(rss_after_open - rss0, 1),
        'rss_delta_mb': round(peak - rss_after_open, 1),
        'passes': bool(peak - rss_after_open < 2048),
    }
    print(json.dumps(out))
    if not args.keep:
        os.unlink(args.path)


if __name__ == '__main__':
    main()
