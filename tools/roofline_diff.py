#!/usr/bin/env python
"""Diff two roofline records: headroom reclaimed (or lost) per layer.

The roofline block (MXTPU_ROOFLINE=1) names every layer's class and
estimated headroom; this tool closes the loop on an optimization
round by diffing a before/after pair::

    python tools/roofline_diff.py before.jsonl after.jsonl

Each argument is either a telemetry JSONL log (the LAST ``roofline``
record wins, like tools/roofline_report.py) or a BENCH_r*.json
artifact (the ``telemetry.roofline`` section, harness wrapper or raw
JSON-lines form — bench truncates its ``layers`` list to the summary
top-N, so a JSONL log is the complete view).

Layers are matched by name. For each: time delta, headroom delta
(positive ``reclaimed`` = the after-run sits closer to its roofline),
and the class transition when one happened. Ranked by headroom
reclaimed, worst regression last, with step-time and whole-program
totals — the "re-measure" step of docs/perf.md's "Closing the MFU
gap" worked example. Layers present on only one side are listed (a
renamed scope or a remat-policy flip can legitimately add/remove
layers); ``--json`` dumps the raw diff for scripting.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
TOOLS = os.path.join(REPO, 'tools')
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)


def load_roofline(path):
    """The authoritative roofline analysis dict out of one artifact:
    a telemetry JSONL's last roofline/summary record, or a bench
    artifact's telemetry.roofline section."""
    with open(path) as f:
        text = f.read()
    # bench artifact first: one JSON dict (harness wrapper or bare
    # metric dict), or bench stdout JSON lines
    for candidate in _json_candidates(text):
        roof = _bench_roofline(candidate)
        if roof is not None:
            return roof
    # telemetry JSONL: reuse the report tools' loader conventions
    from telemetry_report import load
    from roofline_report import roofline_records
    recs = roofline_records(load(path))
    if recs:
        return recs[-1][1]
    raise SystemExit(
        'roofline_diff: %s holds no roofline record (need a telemetry '
        'JSONL from MXTPU_ROOFLINE=1 or a BENCH json with a '
        'telemetry.roofline section)' % path)


def _json_candidates(text):
    try:
        data = json.loads(text)
        if isinstance(data, dict):
            yield data
            if isinstance(data.get('parsed'), dict):
                yield data['parsed']
    except ValueError:
        pass
    for line in reversed(text.strip().splitlines()):
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict):
            yield d


def _bench_roofline(rec):
    tel = rec.get('telemetry')
    if isinstance(tel, dict) and isinstance(tel.get('roofline'), dict):
        return tel['roofline']
    if isinstance(rec.get('roofline'), dict):   # bare telemetry section
        return rec['roofline']
    return None


def diff(old, new):
    """The layer-matched diff dict of two analysis dicts."""
    o_layers = {r['layer']: r for r in old.get('layers') or []}
    n_layers = {r['layer']: r for r in new.get('layers') or []}
    rows = []
    for layer in sorted(set(o_layers) & set(n_layers)):
        o, n = o_layers[layer], n_layers[layer]
        oh, nh = o.get('headroom_ms'), n.get('headroom_ms')
        rows.append({
            'layer': layer,
            'class_old': o.get('class'), 'class_new': n.get('class'),
            'time_ms_old': o.get('time_ms'),
            'time_ms_new': n.get('time_ms'),
            'headroom_ms_old': oh, 'headroom_ms_new': nh,
            'reclaimed_ms': round(oh - nh, 4)
            if oh is not None and nh is not None else None,
        })
    rows.sort(key=lambda r: -(r['reclaimed_ms'] or 0.0))
    total = round(sum(r['reclaimed_ms'] or 0.0 for r in rows), 4)
    return {
        'program_old': old.get('program'), 'program_new': new.get('program'),
        'source_old': old.get('source'), 'source_new': new.get('source'),
        'step_time_ms_old': old.get('step_time_ms'),
        'step_time_ms_new': new.get('step_time_ms'),
        'layers': rows,
        'only_old': sorted(set(o_layers) - set(n_layers)),
        'only_new': sorted(set(n_layers) - set(o_layers)),
        'total_reclaimed_ms': total,
    }


def _fmt(v):
    if v is None:
        return '-'
    return ('%.4f' % float(v)).rstrip('0').rstrip('.') or '0'


def render(d, old_path, new_path, top=None):
    lines = ['roofline diff: %s -> %s' % (old_path, new_path)]
    if d['source_old'] != d['source_new']:
        lines.append('  note: sources differ (%s vs %s) — modeled and '
                     'measured times are not directly comparable'
                     % (d['source_old'], d['source_new']))
    lines.append('  step_time_ms      %s -> %s'
                 % (_fmt(d['step_time_ms_old']),
                    _fmt(d['step_time_ms_new'])))
    rows = d['layers'][:top] if top else d['layers']
    if rows:
        w = max(max(len(r['layer']) for r in rows), len('layer'))
        lines.append('  %-*s %10s %10s %12s  %s'
                     % (w, 'layer', 'time_old', 'time_new',
                        'reclaimed_ms', 'class'))
        for r in rows:
            cls = r['class_new'] if r['class_new'] == r['class_old'] \
                else '%s -> %s' % (r['class_old'], r['class_new'])
            lines.append('  %-*s %10s %10s %12s  %s'
                         % (w, r['layer'], _fmt(r['time_ms_old']),
                            _fmt(r['time_ms_new']),
                            _fmt(r['reclaimed_ms']), cls))
        if top and len(d['layers']) > top:
            lines.append('  (+%d more layers)' % (len(d['layers']) - top))
    for key, label in (('only_old', 'gone in new'),
                       ('only_new', 'new layers')):
        if d[key]:
            lines.append('  %s: %s' % (label, ', '.join(d[key])))
    lines.append('  total headroom reclaimed: %s ms/step'
                 % _fmt(d['total_reclaimed_ms']))
    return '\n'.join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='Diff two roofline records (telemetry JSONL or '
                    'BENCH json): per-layer headroom reclaimed, class '
                    'transitions, step-time movement — the re-measure '
                    'step of the MFU-gap workflow (docs/perf.md).')
    ap.add_argument('old', help='baseline artifact (JSONL or BENCH json)')
    ap.add_argument('new', help='candidate artifact (JSONL or BENCH json)')
    ap.add_argument('--top', type=int, default=16,
                    help='rows rendered (default 16; 0 = all)')
    ap.add_argument('--json', action='store_true',
                    help='dump the raw diff dict as JSON instead')
    args = ap.parse_args(argv)
    d = diff(load_roofline(args.old), load_roofline(args.new))
    if args.json:
        print(json.dumps(d, indent=2))
        return 0
    print(render(d, args.old, args.new, top=args.top or None))
    return 0


if __name__ == '__main__':
    sys.exit(main())
