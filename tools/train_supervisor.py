#!/usr/bin/env python
"""Whole-process training supervision: restart a dying run from the
outside.

``module/resilient_fit.py`` restarts a run that fails *inside* the
process (a TrainingHealthError, a dispatch exception). This wrapper
covers the failures it cannot: host loss, a wedged backend that takes
the interpreter down, an OOM kill, a segfaulting runtime. It launches
any training command as a child process and, while the restart budget
lasts, relaunches it after an unclean exit::

    python tools/train_supervisor.py -- python train.py --epochs 90
    MXTPU_RESTART_MAX=5 MXTPU_RESTART_BACKOFF=10 \
        python tools/train_supervisor.py --log sup.jsonl -- python train.py

Liveness tier (``--liveness`` / MXTPU_SUPERVISOR_LIVENESS): a child can
hang without dying — a collective waiting on a lost peer wedges every
thread, including the one that would notice. The in-process watchdog
(MXTPU_WATCHDOG_SECS, telemetry/watchdog.py) aborts most of those with
the distinct exit code 85; for a child too wedged even for that, the
supervisor watches the child's telemetry JSONL for growth and
SIGTERM/SIGKILLs + relaunches when it stalls past the threshold, against
the same restart budget.

Restart-from-last-good comes for free: the child is expected to run
with ``MXTPU_CKPT_DIR``/``MXTPU_CKPT_EVERY`` set (the supervisor warns
when they are not), so each relaunch resumes from the newest
health-certified checkpoint via the module's own MXTPU_CKPT_RESUME
path — the supervisor never parses or rewrites training state itself.

Every restart is recorded as a ``restart`` JSONL record (appended to
``--log``, or to the child's MXTPU_TELEMETRY_PATH so the run's own
telemetry log carries its restart history) and the final record
summarizes the outcome. Exit code: the child's last exit code.

Budget/backoff share the in-process driver's flags: MXTPU_RESTART_MAX
attempts, MXTPU_RESTART_BACKOFF * 2^(k-1) seconds between them (capped
at 60s). A clean exit (code 0) or SIGINT stops the loop immediately.

This tier supervises ONE process. A real multi-host job (W workers in
one jax.distributed gang) dies as a unit — the survivors of a lost
worker wedge in collectives that can never complete — so it needs
``tools/gang_supervisor.py``, which launches and relaunches the W
workers as a gang on this module's budget/backoff/liveness policy.
"""
import argparse
import json
import os
import signal
import subprocess
import sys
import time

_BACKOFF_CAP_S = 60.0

# exit codes that restarting cannot help: misuse of the CLI itself
_NO_RETRY_CODES = (2,)


def backoff_delay(attempt, backoff):
    """Delay before restart ``attempt`` (1-based): backoff * 2^(k-1),
    capped. Shared with tools/gang_supervisor.py — one budget/backoff
    policy for both supervision tiers."""
    return min(_BACKOFF_CAP_S, backoff * (2.0 ** (attempt - 1)))

# the in-process hang watchdog's distinct abort code
# (mxnet_tpu/telemetry/watchdog.py HANG_EXIT_CODE — mirrored here
# because the supervisor never imports the framework)
_HANG_EXIT = 85

_LIVENESS_POLL_S = 2.0
_TERM_GRACE_S = 15.0


def _env_int(name, default):
    try:
        return int(os.environ.get(name, '') or default)
    except ValueError:
        return default


def _env_float(name, default):
    try:
        return float(os.environ.get(name, '') or default)
    except ValueError:
        return default


def _record(path, rec):
    if not path:
        return
    try:
        with open(path, 'a') as f:
            f.write(json.dumps(rec) + '\n')
    except OSError as e:
        print('train_supervisor: cannot append to %s (%s)' % (path, e),
              file=sys.stderr)


def lost_work_secs(attempt_elapsed, ckpt_dir=None, now=None):
    """Wall seconds a dead attempt loses to the goodput ledger:
    everything since the last-good checkpoint pointer was certified
    (the ``last_good.step`` file's mtime — the framework-free mirror of
    module/checkpointing.py's pointer contract), clamped to the
    attempt's own elapsed; the FULL attempt when no pointer exists
    (nothing to resume from — every second re-trains). Shared with
    tools/gang_supervisor.py so both tiers price lost work the same
    way."""
    if ckpt_dir is None:
        ckpt_dir = os.environ.get('MXTPU_CKPT_DIR', '')
    if now is None:
        now = time.time()
    if ckpt_dir:
        try:
            mtime = os.stat(
                os.path.join(ckpt_dir, 'last_good.step')).st_mtime
            return max(0.0, min(float(attempt_elapsed), now - mtime))
        except OSError:
            pass
    return max(0.0, float(attempt_elapsed))


def _describe(code):
    if code is None:
        return 'running'
    if code < 0:
        try:
            return 'killed by signal %s' % signal.Signals(-code).name
        except ValueError:
            return 'killed by signal %d' % -code
    if code == _HANG_EXIT:
        return 'exit code %d (hang watchdog abort)' % code
    return 'exit code %d' % code


def _kill_child(proc):
    """SIGTERM, a grace period, then SIGKILL; returns the exit code."""
    proc.terminate()
    try:
        return proc.wait(timeout=_TERM_GRACE_S)
    except subprocess.TimeoutExpired:
        proc.kill()
        return proc.wait()


class FileStallWatch:
    """The liveness stall rule over ONE file — shared by the
    single-child tier here and tools/gang_supervisor.py's per-worker
    watches, so the two supervision tiers cannot drift on the policy:

    - the stat is (size, mtime), not size alone: a sink that hit its
      MXTPU_TELEMETRY_MAX_MB cap stops GROWING for good but keeps
      touching the file's mtime at the flush cadence, so a
      healthy-but-capped child never reads as a hang;
    - arm at the FIRST observed change (the in-process watchdog's
      arm-at-first-mark rule): a child that never writes the file at
      all — telemetry accidentally off, path misconfigured — degrades
      to plain restart-on-exit supervision instead of a
      kill-and-relaunch loop of healthy children. The long quiet
      stretch AFTER the start record (first XLA compile) is still on
      the operator: the threshold must exceed it
      (docs/reliability.md)."""

    def __init__(self, path, secs):
        self.path = path
        self.secs = secs
        self.last = self._stat()
        self.changed = time.time()
        self.armed = False

    def _stat(self):
        try:
            st = os.stat(self.path)
            return st.st_size, st.st_mtime
        except OSError:
            return None   # not created yet

    def stalled(self):
        """Seconds past the last change when armed + over threshold,
        else None (also refreshes the watch)."""
        now = time.time()
        cur = self._stat()
        if cur != self.last:
            self.last = cur
            self.changed = now
            self.armed = True
            return None
        if self.armed and now - self.changed > self.secs:
            return now - self.changed
        return None


def _wait_with_liveness(proc, path, secs, quiet=False):
    """Wait for the child, additionally requiring its telemetry JSONL
    at ``path`` to GROW at least every ``secs`` seconds — the
    supervisor-side liveness tier for a child too wedged to run its own
    in-process watchdog (a stuck collective blocks every thread that
    could observe a timer; file growth stops, and only an outside
    process can act). Returns (exit_code, timed_out). The child's sink
    flushes at least every few seconds (telemetry/export.py
    _FLUSH_SECS), so buffering cannot masquerade as a hang; the stall
    rule itself lives in :class:`FileStallWatch`."""
    watch = FileStallWatch(path, secs)
    while True:
        try:
            return proc.wait(timeout=_LIVENESS_POLL_S), False
        except subprocess.TimeoutExpired:
            pass
        stalled = watch.stalled()
        if stalled is not None:
            if not quiet:
                print('train_supervisor: child wrote no telemetry '
                      'records for %.0fs (liveness %.0fs) — killing the '
                      'wedged child' % (stalled, secs), file=sys.stderr)
            return _kill_child(proc), True


def run(cmd, restart_max, backoff, log_path, quiet=False,
        liveness=0.0, liveness_path=None):
    """Supervise one training command; returns its final exit code.
    ``liveness`` > 0 additionally kills + relaunches a child whose
    telemetry JSONL (``liveness_path``) stops growing for that many
    seconds — the tier for a child too wedged to self-abort."""
    attempts = 0
    # cumulative lost-work seconds across relaunches, seeded from the
    # environment so chained supervisors keep one running total; each
    # child reads it back as MXTPU_GOODPUT_LOST_S and reports
    # prior_lost_s / job_goodput_pct in its goodput record
    lost_total = _env_float('MXTPU_GOODPUT_LOST_S', 0.0)
    while True:
        t0 = time.time()
        timed_out = False
        env = dict(os.environ)
        env['MXTPU_GOODPUT_LOST_S'] = '%.3f' % lost_total
        try:
            proc = subprocess.Popen(cmd, env=env)
        except OSError as e:
            print('train_supervisor: cannot launch %r (%s)'
                  % (cmd[0], e), file=sys.stderr)
            return 127
        try:
            if liveness > 0 and liveness_path:
                code, timed_out = _wait_with_liveness(
                    proc, liveness_path, liveness, quiet=quiet)
            else:
                code = proc.wait()
        except KeyboardInterrupt:
            # the operator wants the run down: forward and stop —
            # an interactive stop is never a fault to retry
            proc.send_signal(signal.SIGINT)
            try:
                code = proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                code = proc.wait()
            _record(log_path, {'type': 'restart', 'attempt': attempts,
                               'final': True, 'reason': 'KeyboardInterrupt',
                               'exit_code': code})
            return code
        elapsed = time.time() - t0
        if code == 0 and not timed_out:
            if attempts and not quiet:
                print('train_supervisor: run completed after %d restart(s)'
                      % attempts, file=sys.stderr)
            _record(log_path, {'type': 'restart', 'attempt': attempts,
                               'final': True, 'reason': 'clean_exit',
                               'exit_code': 0})
            return 0
        # a liveness kill is NEVER a clean exit, whatever code the
        # child's SIGTERM handler chose (save-and-exit-0 is common):
        # the run was wedged mid-training and must relaunch
        if (code in _NO_RETRY_CODES and not timed_out) \
                or attempts >= restart_max:
            _record(log_path, {'type': 'restart', 'attempt': attempts,
                               'final': True, 'reason': 'budget_exhausted'
                               if code not in _NO_RETRY_CODES else 'usage',
                               'exit_code': code})
            if not quiet:
                print('train_supervisor: giving up after %d attempt(s) '
                      '(%s)' % (attempts + 1, _describe(code)),
                      file=sys.stderr)
            # never report success for a run abandoned mid-training
            return code if not (timed_out and code == 0) else 1
        attempts += 1
        delay = backoff_delay(attempts, backoff)
        lost = lost_work_secs(elapsed)
        lost_total += lost
        _record(log_path, {'type': 'restart', 'attempt': attempts,
                           'reason': 'liveness_timeout' if timed_out
                           else 'process_exit',
                           'message': _describe(code), 'exit_code': code,
                           'elapsed_s': round(elapsed, 1),
                           'lost_s': round(lost, 1),
                           'lost_total_s': round(lost_total, 1),
                           'backoff_s': delay})
        if not quiet:
            print('train_supervisor: attempt %d/%d died (%s after %.0fs) '
                  '— relaunching in %.1fs'
                  % (attempts, restart_max, _describe(code), elapsed,
                     delay), file=sys.stderr)
        if delay:
            try:
                time.sleep(delay)
            except KeyboardInterrupt:
                # operator stop between attempts: no child to forward
                # to — close the record stream with the same terminal
                # record the mid-run Ctrl-C path writes
                _record(log_path, {'type': 'restart', 'attempt': attempts,
                                   'final': True,
                                   'reason': 'KeyboardInterrupt',
                                   'exit_code': code})
                return code


def main(argv=None):
    p = argparse.ArgumentParser(
        description='Run a training command under restart supervision '
                    '(relaunch after unclean exits, restart budget + '
                    'exponential backoff from MXTPU_RESTART_*).')
    p.add_argument('--restart-max', type=int, default=None,
                   help='restart budget (default: MXTPU_RESTART_MAX or 3)')
    p.add_argument('--backoff', type=float, default=None,
                   help='base backoff seconds '
                        '(default: MXTPU_RESTART_BACKOFF or 2)')
    p.add_argument('--log', default=None,
                   help='JSONL file for restart records (default: the '
                        "child's MXTPU_TELEMETRY_PATH when set)")
    p.add_argument('--liveness', type=float, default=None,
                   help='kill + relaunch the child when its telemetry '
                        'JSONL stops growing for this many seconds — '
                        'the tier for a child too wedged to self-abort '
                        '(default: MXTPU_SUPERVISOR_LIVENESS or 0 = off; '
                        'needs the child run with MXTPU_TELEMETRY=1)')
    p.add_argument('--quiet', action='store_true',
                   help='suppress supervisor stderr chatter')
    p.add_argument('cmd', nargs=argparse.REMAINDER,
                   help='training command (prefix with -- )')
    args = p.parse_args(argv)
    cmd = list(args.cmd)
    if cmd and cmd[0] == '--':
        cmd = cmd[1:]
    if not cmd:
        p.error('no training command given (append: -- python train.py ...)')
    restart_max = args.restart_max if args.restart_max is not None \
        else _env_int('MXTPU_RESTART_MAX', 3)
    backoff = args.backoff if args.backoff is not None \
        else _env_float('MXTPU_RESTART_BACKOFF', 2.0)
    log_path = args.log or os.environ.get('MXTPU_TELEMETRY_PATH')
    liveness = args.liveness if args.liveness is not None \
        else _env_float('MXTPU_SUPERVISOR_LIVENESS', 0.0)
    liveness_path = os.environ.get('MXTPU_TELEMETRY_PATH')
    if liveness > 0 and not liveness_path:
        print('train_supervisor: --liveness needs the child run with '
              'MXTPU_TELEMETRY=1 and MXTPU_TELEMETRY_PATH set (the '
              'liveness signal is that file growing) — liveness '
              'disabled', file=sys.stderr)
        liveness = 0.0
    if not args.quiet and not os.environ.get('MXTPU_CKPT_DIR'):
        print('train_supervisor: MXTPU_CKPT_DIR is not set — restarts '
              'will rerun from epoch 0 (set MXTPU_CKPT_DIR and '
              'MXTPU_CKPT_EVERY so relaunches resume from the last-good '
              'checkpoint)', file=sys.stderr)
    return run(cmd, restart_max, backoff, log_path, quiet=args.quiet,
               liveness=liveness, liveness_path=liveness_path)


if __name__ == '__main__':
    sys.exit(main())
