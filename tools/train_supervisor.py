#!/usr/bin/env python
"""Whole-process training supervision: restart a dying run from the
outside.

``module/resilient_fit.py`` restarts a run that fails *inside* the
process (a TrainingHealthError, a dispatch exception). This wrapper
covers the failures it cannot: host loss, a wedged backend that takes
the interpreter down, an OOM kill, a segfaulting runtime. It launches
any training command as a child process and, while the restart budget
lasts, relaunches it after an unclean exit::

    python tools/train_supervisor.py -- python train.py --epochs 90
    MXTPU_RESTART_MAX=5 MXTPU_RESTART_BACKOFF=10 \
        python tools/train_supervisor.py --log sup.jsonl -- python train.py

Restart-from-last-good comes for free: the child is expected to run
with ``MXTPU_CKPT_DIR``/``MXTPU_CKPT_EVERY`` set (the supervisor warns
when they are not), so each relaunch resumes from the newest
health-certified checkpoint via the module's own MXTPU_CKPT_RESUME
path — the supervisor never parses or rewrites training state itself.

Every restart is recorded as a ``restart`` JSONL record (appended to
``--log``, or to the child's MXTPU_TELEMETRY_PATH so the run's own
telemetry log carries its restart history) and the final record
summarizes the outcome. Exit code: the child's last exit code.

Budget/backoff share the in-process driver's flags: MXTPU_RESTART_MAX
attempts, MXTPU_RESTART_BACKOFF * 2^(k-1) seconds between them (capped
at 60s). A clean exit (code 0) or SIGINT stops the loop immediately.
"""
import argparse
import json
import os
import signal
import subprocess
import sys
import time

_BACKOFF_CAP_S = 60.0

# exit codes that restarting cannot help: misuse of the CLI itself
_NO_RETRY_CODES = (2,)


def _env_int(name, default):
    try:
        return int(os.environ.get(name, '') or default)
    except ValueError:
        return default


def _env_float(name, default):
    try:
        return float(os.environ.get(name, '') or default)
    except ValueError:
        return default


def _record(path, rec):
    if not path:
        return
    try:
        with open(path, 'a') as f:
            f.write(json.dumps(rec) + '\n')
    except OSError as e:
        print('train_supervisor: cannot append to %s (%s)' % (path, e),
              file=sys.stderr)


def _describe(code):
    if code is None:
        return 'running'
    if code < 0:
        try:
            return 'killed by signal %s' % signal.Signals(-code).name
        except ValueError:
            return 'killed by signal %d' % -code
    return 'exit code %d' % code


def run(cmd, restart_max, backoff, log_path, quiet=False):
    """Supervise one training command; returns its final exit code."""
    attempts = 0
    while True:
        t0 = time.time()
        try:
            proc = subprocess.Popen(cmd)
        except OSError as e:
            print('train_supervisor: cannot launch %r (%s)'
                  % (cmd[0], e), file=sys.stderr)
            return 127
        try:
            code = proc.wait()
        except KeyboardInterrupt:
            # the operator wants the run down: forward and stop —
            # an interactive stop is never a fault to retry
            proc.send_signal(signal.SIGINT)
            try:
                code = proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                code = proc.wait()
            _record(log_path, {'type': 'restart', 'attempt': attempts,
                               'final': True, 'reason': 'KeyboardInterrupt',
                               'exit_code': code})
            return code
        elapsed = time.time() - t0
        if code == 0:
            if attempts and not quiet:
                print('train_supervisor: run completed after %d restart(s)'
                      % attempts, file=sys.stderr)
            _record(log_path, {'type': 'restart', 'attempt': attempts,
                               'final': True, 'reason': 'clean_exit',
                               'exit_code': 0})
            return 0
        if code in _NO_RETRY_CODES or attempts >= restart_max:
            _record(log_path, {'type': 'restart', 'attempt': attempts,
                               'final': True, 'reason': 'budget_exhausted'
                               if code not in _NO_RETRY_CODES else 'usage',
                               'exit_code': code})
            if not quiet:
                print('train_supervisor: giving up after %d attempt(s) '
                      '(%s)' % (attempts + 1, _describe(code)),
                      file=sys.stderr)
            return code
        attempts += 1
        delay = min(_BACKOFF_CAP_S, backoff * (2.0 ** (attempts - 1)))
        _record(log_path, {'type': 'restart', 'attempt': attempts,
                           'reason': 'process_exit',
                           'message': _describe(code), 'exit_code': code,
                           'elapsed_s': round(elapsed, 1),
                           'backoff_s': delay})
        if not quiet:
            print('train_supervisor: attempt %d/%d died (%s after %.0fs) '
                  '— relaunching in %.1fs'
                  % (attempts, restart_max, _describe(code), elapsed,
                     delay), file=sys.stderr)
        if delay:
            try:
                time.sleep(delay)
            except KeyboardInterrupt:
                # operator stop between attempts: no child to forward
                # to — close the record stream with the same terminal
                # record the mid-run Ctrl-C path writes
                _record(log_path, {'type': 'restart', 'attempt': attempts,
                                   'final': True,
                                   'reason': 'KeyboardInterrupt',
                                   'exit_code': code})
                return code


def main(argv=None):
    p = argparse.ArgumentParser(
        description='Run a training command under restart supervision '
                    '(relaunch after unclean exits, restart budget + '
                    'exponential backoff from MXTPU_RESTART_*).')
    p.add_argument('--restart-max', type=int, default=None,
                   help='restart budget (default: MXTPU_RESTART_MAX or 3)')
    p.add_argument('--backoff', type=float, default=None,
                   help='base backoff seconds '
                        '(default: MXTPU_RESTART_BACKOFF or 2)')
    p.add_argument('--log', default=None,
                   help='JSONL file for restart records (default: the '
                        "child's MXTPU_TELEMETRY_PATH when set)")
    p.add_argument('--quiet', action='store_true',
                   help='suppress supervisor stderr chatter')
    p.add_argument('cmd', nargs=argparse.REMAINDER,
                   help='training command (prefix with -- )')
    args = p.parse_args(argv)
    cmd = list(args.cmd)
    if cmd and cmd[0] == '--':
        cmd = cmd[1:]
    if not cmd:
        p.error('no training command given (append: -- python train.py ...)')
    restart_max = args.restart_max if args.restart_max is not None \
        else _env_int('MXTPU_RESTART_MAX', 3)
    backoff = args.backoff if args.backoff is not None \
        else _env_float('MXTPU_RESTART_BACKOFF', 2.0)
    log_path = args.log or os.environ.get('MXTPU_TELEMETRY_PATH')
    if not args.quiet and not os.environ.get('MXTPU_CKPT_DIR'):
        print('train_supervisor: MXTPU_CKPT_DIR is not set — restarts '
              'will rerun from epoch 0 (set MXTPU_CKPT_DIR and '
              'MXTPU_CKPT_EVERY so relaunches resume from the last-good '
              'checkpoint)', file=sys.stderr)
    return run(cmd, restart_max, backoff, log_path, quiet=args.quiet)


if __name__ == '__main__':
    sys.exit(main())
