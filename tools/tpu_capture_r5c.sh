#!/bin/bash
# Chained round-5 capture, part C: BN one-pass A/B (the round-5 attack
# on the ~5.5 ms of non-conv HBM passes in the ResNet step — VERDICT
# r4 weak#1). Part A's default bench step runs with MXTPU_BN_ONEPASS=1
# (the new default); this banks the =0 control at identical config so
# the delta is the one removed HBM read of every BN input activation.
#
# Launch detached:
#   setsid nohup bash tools/tpu_capture_r5c.sh > /tmp/capture_r5c.log 2>&1 < /dev/null &
set -u
cd "$(dirname "$0")/.."
. tools/tpu_capture_lib.sh
OUT=docs/tpu_artifacts
mkdir -p "$OUT"
STAMP=$(date -u +%Y%m%dT%H%M%SZ)
echo "R5C CAPTURE STAMP=$STAMP"

wait_for_predecessor /tmp/capture_r5b.log \
  'R5B CAPTURE ALL DONE|gave up before' 'tools/tpu_capture_r5b\.sh'

probe_until_healthy || { echo "gave up before bn A/B"; exit 1; }
echo "== bench (MXTPU_BN_ONEPASS=0 control) =="
MXTPU_BN_ONEPASS=0 MXTPU_BENCH_BUDGET=900 timeout 1200 python bench.py \
  > "$OUT/bench_bn_twopass_$STAMP.json" 2> "$OUT/bench_bn_twopass_$STAMP.log"
echo "rc=$?"; tail -1 "$OUT/bench_bn_twopass_$STAMP.json"
grep -o "loss=[^,]*" "$OUT/bench_bn_twopass_$STAMP.log" | tail -1

# one-pass run chasing the same window, so the A/B pair is comparable
probe_until_healthy || { echo "gave up before bn onepass"; exit 1; }
echo "== bench (MXTPU_BN_ONEPASS=1, same window) =="
MXTPU_BN_ONEPASS=1 MXTPU_BENCH_BUDGET=900 timeout 1200 python bench.py \
  > "$OUT/bench_bn_onepass_$STAMP.json" 2> "$OUT/bench_bn_onepass_$STAMP.log"
echo "rc=$?"; tail -1 "$OUT/bench_bn_onepass_$STAMP.json"
grep -o "loss=[^,]*" "$OUT/bench_bn_onepass_$STAMP.log" | tail -1

echo "== R5C CAPTURE ALL DONE =="
