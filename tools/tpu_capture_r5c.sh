#!/bin/bash
# Chained round-5 capture, part C: BN one-pass A/B (the round-5 attack
# on the ~5.5 ms of non-conv HBM passes in the ResNet step — VERDICT
# r4 weak#1). Part A's default bench step runs with MXTPU_BN_ONEPASS=1
# (the new default); this banks the =0 control at identical config so
# the delta is the one removed HBM read of every BN input activation.
#
# Launch detached:
#   setsid nohup bash tools/tpu_capture_r5c.sh > /tmp/capture_r5c.log 2>&1 < /dev/null &
set -u
cd "$(dirname "$0")/.."
OUT=docs/tpu_artifacts
mkdir -p "$OUT"
STAMP=$(date -u +%Y%m%dT%H%M%SZ)
echo "R5C CAPTURE STAMP=$STAMP"

for i in $(seq 1 100); do
  if grep -q 'R5B CAPTURE ALL DONE\|gave up before' /tmp/capture_r5b.log 2>/dev/null; then
    echo "part B finished (sentinel)"
    break
  fi
  if ! pgrep -f 'tools/tpu_capture_r5b\.sh' > /dev/null 2>&1; then
    echo "part B process gone"
    break
  fi
  sleep 360
done

probe_until_healthy() {
  for i in $(seq 1 40); do
    echo "$(date -u +%H:%M:%S) probe $i"
    if timeout 240 python -c 'import jax; assert any(d.platform=="tpu" for d in jax.devices())' 2>/dev/null; then
      echo "$(date -u +%H:%M:%S) chip healthy"
      return 0
    fi
    sleep 480
  done
  return 1
}

probe_until_healthy || { echo "gave up before bn A/B"; exit 1; }
echo "== bench (MXTPU_BN_ONEPASS=0 control) =="
MXTPU_BN_ONEPASS=0 MXTPU_BENCH_BUDGET=900 timeout 1200 python bench.py \
  > "$OUT/bench_bn_twopass_$STAMP.json" 2> "$OUT/bench_bn_twopass_$STAMP.log"
echo "rc=$?"; tail -1 "$OUT/bench_bn_twopass_$STAMP.json"
grep -o "loss=[^,]*" "$OUT/bench_bn_twopass_$STAMP.log" | tail -1

# one-pass run under the same fresh window, so the A/B shares a window
probe_until_healthy || { echo "gave up before bn onepass"; exit 1; }
echo "== bench (MXTPU_BN_ONEPASS=1, same window) =="
MXTPU_BN_ONEPASS=1 MXTPU_BENCH_BUDGET=900 timeout 1200 python bench.py \
  > "$OUT/bench_bn_onepass_$STAMP.json" 2> "$OUT/bench_bn_onepass_$STAMP.log"
echo "rc=$?"; tail -1 "$OUT/bench_bn_onepass_$STAMP.json"
grep -o "loss=[^,]*" "$OUT/bench_bn_onepass_$STAMP.log" | tail -1

echo "== R5C CAPTURE ALL DONE =="
