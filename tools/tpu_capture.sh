#!/bin/bash
# One-shot TPU evidence capture. Run when the axon tunnel is healthy
# (e.g. triggered by a probe loop): records everything the TPU-gated
# verdict items need into docs/tpu_artifacts/.
#
#   bash tools/tpu_capture.sh
#
# Captures:
#   1. tests/tpu consistency tier (MXTPU_TEST_TPU=1)
#   2. bench.py (default path)
#   3. bench.py with MXTPU_CONV_BWD_PATCHES=1 (the grad-weight lever)
set -u
cd "$(dirname "$0")/.."
OUT=docs/tpu_artifacts
mkdir -p "$OUT"
STAMP=$(date -u +%Y%m%dT%H%M%SZ)

echo "== probing chip =="
timeout 240 python -c 'import jax; d=jax.devices(); print("TPU OK:", d)' \
  || { echo "chip unreachable; aborting"; exit 1; }

echo "== 1/9 TPU consistency tier =="
MXTPU_TEST_TPU=1 timeout 3000 python -m pytest tests/tpu -v \
  > "$OUT/tpu_consistency_$STAMP.log" 2>&1
echo "rc=$? (log: $OUT/tpu_consistency_$STAMP.log)"

echo "== 2/9 bench (default) =="
MXTPU_BENCH_BUDGET=900 timeout 1200 python bench.py \
  > "$OUT/bench_default_$STAMP.json" 2> "$OUT/bench_default_$STAMP.log"
echo "rc=$?"; tail -1 "$OUT/bench_default_$STAMP.json"

echo "== 3/9 bench (MXTPU_CONV_BWD_PATCHES=1) =="
MXTPU_CONV_BWD_PATCHES=1 MXTPU_BENCH_BUDGET=900 timeout 1200 python bench.py \
  > "$OUT/bench_patches_$STAMP.json" 2> "$OUT/bench_patches_$STAMP.log"
echo "rc=$?"; tail -1 "$OUT/bench_patches_$STAMP.json"

echo "== 4/9 bench (transformer MFU probe) =="
MXTPU_BENCH_MODEL=transformer MXTPU_BENCH_BUDGET=900 timeout 1200 python bench.py \
  > "$OUT/bench_transformer_$STAMP.json" 2> "$OUT/bench_transformer_$STAMP.log"
echo "rc=$?"; tail -1 "$OUT/bench_transformer_$STAMP.json"

echo "== 5/9 bench (steps_per_call=1 A/B: dispatch-bound or compute-bound?) =="
MXTPU_BENCH_STEPS_PER_CALL=1 MXTPU_BENCH_BUDGET=900 timeout 1200 python bench.py \
  > "$OUT/bench_spc1_$STAMP.json" 2> "$OUT/bench_spc1_$STAMP.log"
echo "rc=$?"; tail -1 "$OUT/bench_spc1_$STAMP.json"

echo "== 6/9 pure-JAX control (framework-overhead bound) =="
timeout 900 python tools/purejax_resnet50.py control \
  > "$OUT/purejax_control_$STAMP.json" 2> "$OUT/purejax_control_$STAMP.log"
echo "rc=$?"; tail -1 "$OUT/purejax_control_$STAMP.json"
# per-op breakdown (~20 min): opt-in via MXTPU_CAPTURE_BREAKDOWN=1
if [ -n "${MXTPU_CAPTURE_BREAKDOWN:-}" ]; then
  timeout 2400 python tools/purejax_resnet50.py breakdown \
    > "$OUT/conv_breakdown_$STAMP.json" 2> "$OUT/conv_breakdown_$STAMP.log"
  echo "breakdown rc=$?"
fi

echo "== 7/9 training-table sweep (BASELINE train table cols 1-2) =="
MXTPU_BENCH_MODEL=alexnet MXTPU_BENCH_BUDGET=900 timeout 1200 python bench.py \
  > "$OUT/bench_alexnet_$STAMP.json" 2> "$OUT/bench_alexnet_$STAMP.log"
echo "rc=$?"; tail -1 "$OUT/bench_alexnet_$STAMP.json"
grep -o "loss=[^,]*" "$OUT/bench_alexnet_$STAMP.log" | tail -1  # nan check!
# spc=8: the spc=32 scan-chain warmup at 299px wedged the tunnel once
MXTPU_BENCH_MODEL=inceptionv3 MXTPU_BENCH_STEPS_PER_CALL=8 \
  MXTPU_BENCH_BUDGET=900 timeout 1200 python bench.py \
  > "$OUT/bench_inceptionv3_$STAMP.json" 2> "$OUT/bench_inceptionv3_$STAMP.log"
echo "rc=$?"; tail -1 "$OUT/bench_inceptionv3_$STAMP.json"

echo "== 7b/9 stem space-to-depth A/B (MXTPU_CONV_STEM_S2D; docs/perf.md) =="
MXTPU_CONV_STEM_S2D=1 MXTPU_BENCH_BUDGET=900 timeout 1200 python bench.py \
  > "$OUT/bench_s2d_$STAMP.json" 2> "$OUT/bench_s2d_$STAMP.log"
echo "rc=$?"; tail -1 "$OUT/bench_s2d_$STAMP.json"
MXTPU_CONV_STEM_S2D=1 MXTPU_BENCH_MODEL=alexnet MXTPU_BENCH_BUDGET=600 \
  timeout 900 python bench.py \
  > "$OUT/bench_alexnet_s2d_$STAMP.json" 2> "$OUT/bench_alexnet_s2d_$STAMP.log"
echo "rc=$?"; tail -1 "$OUT/bench_alexnet_s2d_$STAMP.json"
MXTPU_CONV_STEM_S2D=1 MXTPU_BENCH_MODEL=inceptionv3 \
  MXTPU_BENCH_STEPS_PER_CALL=8 MXTPU_BENCH_BUDGET=600 \
  timeout 900 python bench.py \
  > "$OUT/bench_inceptionv3_s2d_$STAMP.json" \
  2> "$OUT/bench_inceptionv3_s2d_$STAMP.log"
echo "rc=$?"; tail -1 "$OUT/bench_inceptionv3_s2d_$STAMP.json"

echo "== 8/9 memory-mirror A/B (BASELINE mirror table; inception-v3) =="
MXTPU_BENCH_MODEL=inceptionv3 MXTPU_BACKWARD_DO_MIRROR=dots \
  MXTPU_BENCH_BUDGET=600 timeout 900 python bench.py \
  > "$OUT/bench_inceptionv3_mirror_$STAMP.json" \
  2> "$OUT/bench_inceptionv3_mirror_$STAMP.log"
echo "rc=$?"; tail -1 "$OUT/bench_inceptionv3_mirror_$STAMP.json"
MXTPU_BENCH_MODEL=inceptionv3 MXTPU_BACKWARD_DO_MIRROR=1 \
  MXTPU_BENCH_BATCH=128 MXTPU_BENCH_BUDGET=600 timeout 900 python bench.py \
  > "$OUT/bench_inceptionv3_mirror_b128_$STAMP.json" \
  2> "$OUT/bench_inceptionv3_mirror_b128_$STAMP.log"
echo "rc=$?"; tail -1 "$OUT/bench_inceptionv3_mirror_b128_$STAMP.json"

echo "== 9/9 inference scoring tier (BASELINE tables 1+3) =="
timeout 3000 python tools/score_bench.py \
  > "$OUT/score_$STAMP.json" 2> "$OUT/score_$STAMP.log"
echo "rc=$?"; tail -1 "$OUT/score_$STAMP.json"

echo "== done; commit docs/tpu_artifacts =="
