"""Fed (non-synthetic) Module.fit throughput: ImageRecordIter feeding
the chip for real (VERDICT r4 #6).

The streaming JPEG pipeline is decode-bound at ~390 img/s on this
one-core host, far under the chip's ~2552 img/s demand, so this bench
uses the two levers built for few-core hosts:

- RAW0 fixed-size records — host work is file reads (np.frombuffer is
  zero-copy), no image codec;
- ``device_augment=1`` — the iterator ships uint8 (B, S, S, C) batches
  (4x smaller upload than f32) and runs random-crop / mirror /
  scale-mean-std as one jitted device call per batch
  (io/__init__.py ImageRecordIter._apply_device_aug).

Model and geometry match the north-star workload: ResNet-50 v1,
3x224x224 crops from 256x256 sources, batch 32, bf16 compute
(MXTPU_F16_AS_BF16 resolves the script-level float16 ask), kvstore
'device', through the unchanged Module.fit (the fused window when
eligible). Reference roles: example/image-classification/train_imagenet
+ src/io/iter_image_recordio_2.cc:122-130 (inline augment).

Prints ONE json line: {"metric": "fed_modulefit_resnet50", ...}.
Budget: MXTPU_BENCH_BUDGET seconds (default 600).
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

N_IMAGES = int(os.environ.get('MXTPU_FED_IMAGES', 2048))
SRC = int(os.environ.get('MXTPU_FED_SRC', 256))
CROP = int(os.environ.get('MXTPU_FED_CROP', 224))
assert CROP <= SRC, 'crop %d exceeds source %d' % (CROP, SRC)
BATCH = int(os.environ.get('MXTPU_BENCH_BATCH', 32))
BUDGET = float(os.environ.get('MXTPU_BENCH_BUDGET', 600))
REC = os.environ.get('MXTPU_FED_REC',
                     '/tmp/fed_rawrnd_%dx%d_%d.rec' % (SRC, SRC, N_IMAGES))


def ensure_rec():
    """Deterministic RAW0 .rec of N fixed-size uint8 images.

    Per-pixel random — INCOMPRESSIBLE, like decoded photos. The
    earlier kron-block images compressed inside the tunnel transport
    and flattered the measured rate ~1.6x past the random-data line
    rate (2026-08-02 probe); a transfer-bound bench must ship data
    with real entropy."""
    from mxnet_tpu.recordio import MXRecordIO, IRHeader, pack_img
    if os.path.exists(REC) and os.path.getsize(REC) > 0:
        return
    rng = np.random.RandomState(0)
    rec = MXRecordIO(REC, 'w')
    for i in range(N_IMAGES):
        img = rng.randint(0, 256, (SRC, SRC, 3), np.uint8)
        rec.write(pack_img(IRHeader(0, float(i % 1000), i, 0), img,
                           img_fmt='.raw'))
    rec.close()


def probe_bw(window=32):
    """Sustained host->device upload MB/s of the fed loop's EXACT
    transfer unit — one stacked (W, B, crop, crop, 3) uint8 window of
    incompressible data — with a host-fetch barrier (block_until_ready
    returns early through the tunnel, and small-chunk probes
    underestimate: per-put overhead dominates 6 MB puts by ~1.7x,
    measured 2026-08-02). The fed number is only interpretable against
    the transport's bandwidth AT MEASUREMENT TIME — the tunnel swings
    2-3x across a session (468 -> 255 img/s on identical configs)."""
    import jax
    import jax.numpy as jnp
    dev = jax.devices()[0]
    rng = np.random.RandomState(0)
    buf = rng.randint(0, 256, (window, BATCH, CROP, CROP, 3), np.uint8)

    def landed(a):
        float(np.asarray(jnp.sum(a[:, :, -1, -1, :].astype(jnp.int32))))

    landed(jax.device_put(buf[:1], dev))            # warm
    t0 = time.perf_counter()
    landed(jax.device_put(buf, dev))
    dt = time.perf_counter() - t0
    return buf.nbytes / dt / 1e6


def main():
    import logging
    # INFO so the artifact log shows "fused fit fast path active" —
    # whether the window path engaged is part of the evidence
    logging.basicConfig(level=logging.INFO)
    os.environ.setdefault('MXTPU_F16_AS_BF16', '1')
    ensure_rec()
    import mxnet_tpu as mx
    import jax
    platform = jax.devices()[0].platform
    bw_before = round(probe_bw(), 1)

    it = mx.io.ImageRecordIter(
        REC, data_shape=(3, CROP, CROP), batch_size=BATCH, shuffle=True,
        rand_crop=1, rand_mirror=1, preprocess_threads=3,
        prefetch_buffer=8, label_name='softmax_label',
        device_augment=1)

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..',
                                    'examples', 'image-classification',
                                    'symbols'))
    import resnet as resnet_sym
    sym = resnet_sym.get_symbol(num_classes=1000, num_layers=50,
                                image_shape="3,%d,%d" % (CROP, CROP), dtype='float16')

    ctx = mx.gpu() if platform != 'cpu' else mx.cpu()
    mod = mx.mod.Module(sym, context=ctx)
    ticks = []
    t0 = time.time()

    def cb(param):
        ticks.append(time.time())

    epoch = 0
    # the context scope also routes the iterator's device-augment call
    # onto the chip (it places on the CURRENT context)
    with ctx:
        # drive fit epoch-by-epoch until the budget is spent
        while time.time() - t0 < BUDGET * 0.8 and epoch < 50:
            mod.fit(it, num_epoch=epoch + 1, begin_epoch=epoch,
                    optimizer='sgd',
                    optimizer_params=(('learning_rate', 0.1),
                                      ('momentum', 0.9),
                                      ('multi_precision', True)),
                    kvstore='device', eval_metric='acc',
                    batch_end_callback=cb, force_init=(epoch == 0),
                    initializer=mx.init.Xavier())
            epoch += 1
            if len(ticks) * BATCH > 20000:
                break

    # steady state: drop the first quarter (compile + cache warmup)
    n = len(ticks)
    if n < 8:
        raise SystemExit('too few batches measured: %d' % n)
    lo = max(1, n // 4)
    span = ticks[-1] - ticks[lo]
    imgs = (n - 1 - lo) * BATCH
    rate = imgs / span if span > 0 else float('nan')
    bw_after = round(probe_bw(), 1)
    from mxnet_tpu.config import flags
    host_crop = bool(flags.get('MXTPU_HOST_CROP'))
    img_bytes = (CROP if host_crop else SRC) ** 2 * 3
    bw = min(bw_before, bw_after)
    out = {'metric': 'fed_modulefit_resnet50_img_s', 'value': round(rate, 1),
           'unit': 'img/s', 'vs_baseline': round(rate / 181.53, 2),
           'platform': platform, 'batch': BATCH, 'batches': n,
           'src': '%dx%d raw' % (SRC, SRC), 'device_augment': 1,
           'host_crop': int(host_crop), 'img_bytes': img_bytes,
           'upload_mbps_before': bw_before, 'upload_mbps_after': bw_after,
           # transfer-bound ceiling at the measured bandwidth: the
           # fraction of line rate the pipeline achieved is the
           # host-independent claim (the absolute img/s is the tunnel's)
           'line_rate_img_s': round(bw * 1e6 / img_bytes, 1),
           'line_rate_fraction': round(rate * img_bytes / (bw * 1e6), 3),
           'epochs': epoch, 'rec': REC}
    print(json.dumps(out))


if __name__ == '__main__':
    main()
