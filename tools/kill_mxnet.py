#!/usr/bin/env python
"""Kill stray training processes on a cluster (reference
tools/kill-mxnet.py — ssh'ed a ps|grep|kill pipeline to every host in a
hostfile). Same contract here, plus a `local` mode matching
tools/launch.py's local launcher.

    python kill_mxnet.py <hostfile|local> [user] [prog]

With `local`, kills this host's processes whose command line matches
``prog`` (default: mxnet_tpu) and that carry the DMLC_* launch env.
"""
import getpass
import os
import signal
import subprocess
import sys


def _kill_cmd(user, prog):
    return ("ps aux | grep -v grep | grep -v kill_mxnet | grep '%s' | "
            "awk '{if($1==\"%s\")print $2;}' | xargs -r kill -9"
            % (prog, user))


def _has_dmlc_env(pid):
    """launch.py passes the DMLC_* role protocol through the child env,
    not the command line — /proc/<pid>/environ is the truth."""
    try:
        with open('/proc/%d/environ' % pid, 'rb') as f:
            return any(entry.startswith(b'DMLC_')
                       for entry in f.read().split(b'\0'))
    except OSError:
        return False


def kill_local(prog):
    out = subprocess.run(['ps', '-eo', 'pid,command'],
                         capture_output=True, text=True).stdout
    me = os.getpid()
    killed = []
    for line in out.splitlines()[1:]:
        parts = line.strip().split(None, 1)
        if len(parts) != 2:
            continue
        pid, cmd = int(parts[0]), parts[1]
        if pid == me or 'kill_mxnet' in cmd:
            continue
        if prog in cmd and ('launch.py' in cmd or 'DMLC' in cmd
                            or 'kvstore_server' in cmd
                            or _has_dmlc_env(pid)):
            try:
                os.kill(pid, signal.SIGKILL)
                killed.append(pid)
            except ProcessLookupError:
                pass
            except PermissionError:
                print('skipping pid %d (owned by another user)' % pid)
    print('killed %d local processes: %s' % (len(killed), killed))
    return 0


def main():
    if len(sys.argv) < 2:
        print('usage: %s <hostfile|local> [user] [prog]' % sys.argv[0])
        return 1
    target = sys.argv[1]
    user = sys.argv[2] if len(sys.argv) > 2 else getpass.getuser()
    prog = sys.argv[3] if len(sys.argv) > 3 else 'mxnet_tpu'
    if target == 'local':
        return kill_local(prog)
    with open(target) as f:
        hosts = [h.strip() for h in f if h.strip()]
    cmd = _kill_cmd(user, prog)
    print(cmd)
    for host in hosts:
        print('killing on %s' % host)
        subprocess.run(['ssh', '-o', 'StrictHostKeyChecking=no',
                        '%s@%s' % (user, host), cmd])
    print('Done killing %r for %r on %d hosts' % (prog, user, len(hosts)))
    return 0


if __name__ == '__main__':
    sys.exit(main())
