#!/usr/bin/env python
"""Render a telemetry JSONL log into the end-of-run summary table,
offline.

A trace captured on a remote/CI machine (MXTPU_TELEMETRY=1 writes
MXTPU_TELEMETRY_PATH) can be read without re-running anything::

    python tools/telemetry_report.py telemetry.jsonl

Uses the SAME renderer as the live end-of-run summary
(mxnet_tpu/telemetry/export.py::summary_table), so the offline table
is byte-identical to what the run would have logged. When the log has
a ``summary`` record (written by telemetry.write_summary / the atexit
hook) its registry snapshot and per-program table render directly; a
log from a crashed run (no summary record) is reconstructed
best-effort from the individual span / compile / program records —
counters that only live in the registry (fit.steps etc.) cannot be
recovered that way and the table says so.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from mxnet_tpu.telemetry.export import summary_table  # noqa: E402


def load(path):
    """Parse a JSONL telemetry log (bad lines are skipped, counted)."""
    records, bad = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                bad += 1
    if bad:
        sys.stderr.write('telemetry_report: skipped %d unparseable '
                         'line(s)\n' % bad)
    return records


def _percentile(sorted_vals, p):
    """Nearest-rank, mirroring registry.Histogram.percentile."""
    if not sorted_vals:
        return None
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _reconstruct_health(records):
    """Run-health dict rebuilt from individual ``health`` / ``anomaly``
    records — exactly what a crashed run wants visible: the incidents
    and the LAST anomaly before the crash. None when the run recorded
    neither (health off, or a clean run)."""
    incidents = []
    anomaly_counts = {}
    last_anomaly = None
    input_bound = None
    for r in records:
        typ = r.get('type')
        if typ == 'health' and r.get('event') == 'nonfinite':
            incidents.append({k: v for k, v in r.items()
                              if k not in ('type', 't')})
        elif typ == 'health' and r.get('event') == 'input_bound':
            input_bound = r.get('input_bound_pct')
        elif typ == 'anomaly':
            name = r.get('detector', '?')
            anomaly_counts[name] = anomaly_counts.get(name, 0) + 1
            last_anomaly = {k: v for k, v in r.items()
                            if k not in ('type', 't')}
    if not incidents and not anomaly_counts and input_bound is None:
        return None
    out = {'nonfinite_steps': len(incidents), 'incidents': incidents[:8],
           'anomaly_counts': anomaly_counts, 'last_anomaly': last_anomaly}
    if input_bound is not None:
        out['input_bound_pct'] = input_bound
    return out


def _reconstruct(records):
    """(snapshot, elapsed_s, programs, health) rebuilt from individual
    records — the crashed-run path (no summary record was ever
    written)."""
    spans = {}
    counters = {}
    programs = {}
    times = [r['t'] for r in records if isinstance(r.get('t'), (int, float))]
    for r in records:
        typ = r.get('type')
        if typ == 'span' and isinstance(r.get('dur_ms'), (int, float)):
            spans.setdefault(r.get('name', '?'), []).append(r['dur_ms'])
        elif typ == 'compile':
            counters['xla.compiles'] = counters.get('xla.compiles', 0) + 1
            counters['xla.compile_secs'] = round(
                counters.get('xla.compile_secs', 0.0)
                + float(r.get('dur_s', 0.0)), 4)
        elif typ == 'cache_hit':
            counters['xla.cache_hits'] = \
                counters.get('xla.cache_hits', 0) + 1
        elif typ == 'program':
            name = r.get('name', '?')
            rec = programs.setdefault(
                name, {'name': name, 'compiles': 0, 'dispatches': 0})
            rec['compiles'] += 1
            for f in ('flops', 'bytes_accessed', 'temp_bytes',
                      'argument_bytes', 'output_bytes',
                      'generated_code_bytes'):
                # largest variant per field — the live registrar's
                # merge semantics (telemetry.programs.note_program)
                rec[f] = max(rec.get(f, 0), r.get(f, 0))
    hists = {}
    for name, vals in spans.items():
        vs = sorted(vals)
        hists[name] = {'count': len(vs), 'sum': sum(vs),
                       'mean': sum(vs) / len(vs), 'min': vs[0],
                       'max': vs[-1], 'p50': _percentile(vs, 50),
                       'p95': _percentile(vs, 95)}
    snapshot = {'counters': counters, 'gauges': {}, 'histograms': hists}
    elapsed = (max(times) - min(times)) if len(times) > 1 else None
    return snapshot, elapsed, programs or None, _reconstruct_health(records)


def render(records):
    """The summary table for a parsed record list, as a string."""
    summaries = [r for r in records if r.get('type') == 'summary']
    if summaries:
        s = summaries[-1]
        return summary_table(s.get('snapshot') or {}, s.get('elapsed_s'),
                             programs=s.get('programs'),
                             health=s.get('health'))
    snapshot, elapsed, programs, health = _reconstruct(records)
    table = summary_table(snapshot, elapsed, programs=programs,
                          health=health)
    return table + ('\n(no summary record found — reconstructed from '
                    '%d individual records; registry-only counters and '
                    'gauges are not recoverable)' % len(records))


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='Render a telemetry JSONL log (MXTPU_TELEMETRY_PATH) '
                    'into the end-of-run summary table, offline.')
    ap.add_argument('path', help='telemetry JSONL file to render')
    args = ap.parse_args(argv)
    records = load(args.path)
    if not records:
        sys.stderr.write('telemetry_report: %s holds no records\n'
                         % args.path)
        return 1
    print(render(records))
    return 0


if __name__ == '__main__':
    sys.exit(main())
