#!/usr/bin/env python
"""Render telemetry JSONL logs into the end-of-run summary table,
offline.

A trace captured on a remote/CI machine (MXTPU_TELEMETRY=1 writes
MXTPU_TELEMETRY_PATH) can be read without re-running anything::

    python tools/telemetry_report.py telemetry.jsonl

Uses the SAME renderer as the live end-of-run summary
(mxnet_tpu/telemetry/export.py::summary_table), so the offline table
is byte-identical to what the run would have logged. When the log has
a ``summary`` record (written by telemetry.write_summary / the atexit
hook) its registry snapshot and per-program table render directly; a
log from a crashed run (no summary record) is reconstructed
best-effort from the individual span / compile / program records —
counters that only live in the registry (fit.steps etc.) cannot be
recovered that way and the table says so.

Multi-host jobs write one log per host (each record carries the
``host`` field telemetry.cluster stamps). Handing every log to this
tool merges them on that field and renders a per-host comparison —
steps, step-time p50, io-wait share, non-finite steps — plus the same
straggler classification the live cluster aggregation publishes::

    python tools/telemetry_report.py host0.jsonl host1.jsonl ...

A gang run (tools/gang_supervisor.py --log-dir) lays its logs out as
``h<i>.jsonl`` per worker plus ``gang.jsonl`` of host-stamped restart
records; handing the DIRECTORY to this tool globs exactly that layout
— no flag gymnastics::

    python tools/telemetry_report.py /mnt/run1/logs
"""
import argparse
import glob as _glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from mxnet_tpu.telemetry.export import summary_table  # noqa: E402


def expand_paths(paths):
    """Expand directory arguments into the gang-run log layout: the
    sorted ``h<i>.jsonl`` per-worker files plus ``gang.jsonl`` (the
    supervisor's host-stamped restart records — they merge into each
    worker's view through the same ``host`` field every in-process
    record carries). A directory with neither falls back to every
    ``*.jsonl`` it holds; plain file arguments pass through."""
    out = []
    for p in paths:
        if not os.path.isdir(p):
            out.append(p)
            continue
        hosts = sorted(_glob.glob(os.path.join(p, 'h[0-9]*.jsonl')))
        gang = os.path.join(p, 'gang.jsonl')
        found = hosts + ([gang] if os.path.exists(gang) else [])
        if not found:
            found = sorted(_glob.glob(os.path.join(p, '*.jsonl')))
        if not found:
            sys.stderr.write('telemetry_report: %s holds no .jsonl '
                             'logs\n' % p)
            continue
        out.extend(found)
    return out


def load(path):
    """Parse a JSONL telemetry log (bad lines are skipped, counted)."""
    records, bad = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                bad += 1
    if bad:
        sys.stderr.write('telemetry_report: skipped %d unparseable '
                         'line(s)\n' % bad)
    return records


def _percentile(sorted_vals, p):
    """Nearest-rank, mirroring registry.Histogram.percentile."""
    if not sorted_vals:
        return None
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _reconstruct_health(records):
    """Run-health dict rebuilt from individual ``health`` / ``anomaly``
    records — exactly what a crashed run wants visible: the incidents
    and the LAST anomaly before the crash. None when the run recorded
    neither (health off, or a clean run)."""
    incidents = []
    anomaly_counts = {}
    last_anomaly = None
    input_bound = None
    restarts = 0
    hangs = 0
    last_hang = None
    for r in records:
        typ = r.get('type')
        if typ == 'health' and r.get('event') == 'nonfinite':
            incidents.append({k: v for k, v in r.items()
                              if k not in ('type', 't')})
        elif typ == 'health' and r.get('event') == 'input_bound':
            input_bound = r.get('input_bound_pct')
        elif typ == 'anomaly':
            name = r.get('detector', '?')
            anomaly_counts[name] = anomaly_counts.get(name, 0) + 1
            last_anomaly = {k: v for k, v in r.items()
                            if k not in ('type', 't')}
        elif typ == 'restart' and not r.get('final'):
            # one record per supervised restart (resilient_fit /
            # train_supervisor); the supervisor's final summary record
            # repeats the attempt count, so it does not count again
            restarts += 1
        elif typ == 'hang':
            # the watchdog's stall incident (a crashed/aborted run's
            # most important record): count them all, keep the last
            # digest minus the stack dump (the table is a summary —
            # the full stacks stay greppable in the raw log)
            hangs += 1
            last_hang = {k: v for k, v in r.items()
                         if k not in ('type', 't', 'stacks')}
    if not incidents and not anomaly_counts and input_bound is None \
            and not restarts and not hangs:
        return None
    out = {'nonfinite_steps': len(incidents), 'incidents': incidents[:8],
           'anomaly_counts': anomaly_counts, 'last_anomaly': last_anomaly}
    if restarts:
        out['restarts'] = restarts
    if hangs:
        out['hangs'] = hangs
        out['last_hang'] = last_hang
    if input_bound is not None:
        out['input_bound_pct'] = input_bound
    return out


def _reconstruct(records):
    """(snapshot, elapsed_s, programs, health) rebuilt from individual
    records — the crashed-run path (no summary record was ever
    written)."""
    spans = {}
    counters = {}
    programs = {}
    times = [r['t'] for r in records if isinstance(r.get('t'), (int, float))]
    for r in records:
        typ = r.get('type')
        if typ == 'span' and isinstance(r.get('dur_ms'), (int, float)):
            spans.setdefault(r.get('name', '?'), []).append(r['dur_ms'])
        elif typ == 'compile':
            counters['xla.compiles'] = counters.get('xla.compiles', 0) + 1
            counters['xla.compile_secs'] = round(
                counters.get('xla.compile_secs', 0.0)
                + float(r.get('dur_s', 0.0)), 4)
        elif typ == 'cache_hit':
            counters['xla.cache_hits'] = \
                counters.get('xla.cache_hits', 0) + 1
        elif typ == 'program':
            name = r.get('name', '?')
            rec = programs.setdefault(
                name, {'name': name, 'compiles': 0, 'dispatches': 0})
            rec['compiles'] += 1
            for f in ('flops', 'bytes_accessed', 'temp_bytes',
                      'argument_bytes', 'output_bytes',
                      'generated_code_bytes'):
                # largest variant per field — the live registrar's
                # merge semantics (telemetry.programs.note_program)
                rec[f] = max(rec.get(f, 0), r.get(f, 0))
    hists = {}
    for name, vals in spans.items():
        vs = sorted(vals)
        hists[name] = {'count': len(vs), 'sum': sum(vs),
                       'mean': sum(vs) / len(vs), 'min': vs[0],
                       'max': vs[-1], 'p50': _percentile(vs, 50),
                       'p95': _percentile(vs, 95)}
    snapshot = {'counters': counters, 'gauges': {}, 'histograms': hists}
    elapsed = (max(times) - min(times)) if len(times) > 1 else None
    return snapshot, elapsed, programs or None, _reconstruct_health(records)


def _reconstruct_ledger(records):
    """Run-ledger dict rebuilt from raw `manifest` / `scalars` records
    — the crashed-run path (and the fallback for a summary record that
    predates the ledger key). None when the run banked neither."""
    # LAST manifest wins: ledger.begin_run re-emits one per fit() with a
    # run_seq, and the latest describes the run that produced the tail
    # of the log (run_compare keys on the same record)
    man = next((r for r in reversed(records)
                if r.get('type') == 'manifest'), None)
    scalars = [r for r in records if r.get('type') == 'scalars'
               and r.get('event') != 'eval' and r.get('step') is not None]
    if man is None and not scalars:
        return None
    out = {}
    if man is not None:
        from mxnet_tpu.telemetry.ledger import MANIFEST_KEYS
        out['manifest'] = {k: man.get(k) for k in MANIFEST_KEYS
                           if man.get(k) is not None}
        if man.get('env_set'):
            out['manifest']['env_set'] = man['env_set']
    if scalars:
        scalars.sort(key=lambda r: r['step'])
        out['steps'] = int(scalars[-1]['step'])
        deltas = [b['step'] - a['step']
                  for a, b in zip(scalars, scalars[1:])
                  if b['step'] > a['step']]
        out['every'] = min(deltas) if deltas else 0
        recent = scalars[-32:]
        out['recent'] = [{'step': int(r['step']), 'loss': r.get('loss')}
                         for r in recent]
        out['last'] = out['recent'][-1]
        final = next((r.get('loss') for r in reversed(scalars)
                      if r.get('loss') is not None), None)
        if final is not None:
            out['final_loss'] = final
    return out


def _reconstruct_rework(records):
    """Restart-rework steps rebuilt from ``restart`` + ``scalars``
    records — each non-final restart re-trains the span between its
    restore point and the last step the crashed attempt logged before
    it died. Best-effort: a restart with no scalars record preceding it
    contributes nothing (the rework existed, but is unmeasurable from
    this log)."""
    scalars = [(r.get('t'), r['step']) for r in records
               if r.get('type') == 'scalars'
               and isinstance(r.get('step'), (int, float))]
    rework = 0
    for r in records:
        if r.get('type') != 'restart' or r.get('final'):
            continue
        restore = r.get('restore_step')
        t = r.get('t')
        if restore is None or t is None:
            continue
        reached = max((s for ts, s in scalars
                       if ts is not None and ts <= t), default=None)
        if reached is not None:
            rework += max(0, int(reached) - int(restore))
    return rework


def _reconstruct_goodput(records, snapshot, elapsed, roofline, ledger):
    """Goodput attribution recomputed from the reconstructed snapshot —
    the crashed-run path (the process died before summarize() ran).
    Same pure compute as the live ledger, so the offline block cannot
    drift from what the run would have reported."""
    if not elapsed or elapsed <= 0:
        return None
    from mxnet_tpu.telemetry import goodput as _goodput
    comm = ((roofline or {}).get('comm') or {})
    return _goodput.compute(
        snapshot, elapsed,
        rework_steps=_reconstruct_rework(records),
        total_steps=(ledger or {}).get('steps'),
        comm_pct=comm.get('pct_of_step'),
        comm_source=comm.get('source') or ((roofline or {}).get('source')
                                           if comm else None))


def _summary_parts(records):
    """(snapshot, elapsed, programs, health, cluster, roofline, ledger,
    goodput, memory, timeline, reconstructed) for one host's record
    list — the last summary record when present, else the crashed-run
    reconstruction."""
    summaries = [r for r in records if r.get('type') == 'summary']
    clus_recs = [r for r in records if r.get('type') == 'cluster']
    cluster = clus_recs[-1] if clus_recs else None
    if cluster is not None:
        cluster = {k: v for k, v in cluster.items()
                   if k not in ('type', 't', 'host')}
    # the roofline analysis survives a crash as its own record; a clean
    # run also folds it into the summary record (preferred below)
    roof_recs = [r for r in records if r.get('type') == 'roofline']
    roofline = roof_recs[-1] if roof_recs else None
    if roofline is not None:
        roofline = {k: v for k, v in roofline.items()
                    if k not in ('type', 't', 'host')}
    # the memory plane likewise: timeline samples are standalone
    # ``memory`` records (a crashed run's trail), the end-of-run
    # analysis (with the per-layer table) is folded into the summary
    mem_recs = [r for r in records if r.get('type') == 'memory']
    memory = mem_recs[-1] if mem_recs else None
    if memory is not None:
        memory = {k: v for k, v in memory.items()
                  if k not in ('type', 't', 'host')}
    # the step timeline too: every sync round appends a standalone
    # ``timeline`` record (process 0 only), so a crashed run keeps its
    # last critical-path verdict; a clean run folds the final one into
    # the summary record (preferred below)
    tl_recs = [r for r in records if r.get('type') == 'timeline']
    timeline = tl_recs[-1] if tl_recs else None
    if timeline is not None:
        timeline = {k: v for k, v in timeline.items()
                    if k not in ('type', 't', 'host')}
    if summaries:
        s = summaries[-1]
        health = s.get('health')
        restarts = sum(1 for r in records if r.get('type') == 'restart'
                       and not r.get('final'))
        if restarts:
            # supervisor relaunches append restart records from OUTSIDE
            # the process that wrote this summary, so its health.restarts
            # counter never saw them; in-process (resilient_fit) restarts
            # land in both, so max() never double-counts
            health = dict(health or {'nonfinite_steps': 0, 'incidents': [],
                                     'anomaly_counts': {}})
            health['restarts'] = max(int(health.get('restarts') or 0),
                                     restarts)
        hangs = sum(1 for r in records if r.get('type') == 'hang')
        if hangs:
            # same shape for hang incidents: a watchdog-aborted child's
            # hang record precedes the RELAUNCHED child's clean summary
            health = dict(health or {'nonfinite_steps': 0, 'incidents': [],
                                     'anomaly_counts': {}})
            health['hangs'] = max(int(health.get('hangs') or 0), hangs)
        led = s.get('ledger') or _reconstruct_ledger(records)
        roof = s.get('roofline') or roofline
        good = s.get('goodput') or _reconstruct_goodput(
            records, s.get('snapshot') or {}, s.get('elapsed_s'),
            roof, led)
        return (s.get('snapshot') or {}, s.get('elapsed_s'),
                s.get('programs'), health,
                s.get('cluster') or cluster, roof, led, good,
                s.get('memory') or memory, s.get('timeline') or timeline,
                False)
    snapshot, elapsed, programs, health = _reconstruct(records)
    led = _reconstruct_ledger(records)
    good = _reconstruct_goodput(records, snapshot, elapsed, roofline, led)
    return (snapshot, elapsed, programs, health, cluster, roofline,
            led, good, memory, timeline, True)


def render(records):
    """The summary table for a parsed record list, as a string."""
    (snapshot, elapsed, programs, health, cluster, roofline, led, good,
     memory, timeline, reco) = _summary_parts(records)
    table = summary_table(snapshot, elapsed, programs=programs,
                          health=health, cluster=cluster,
                          roofline=roofline, ledger=led, goodput=good,
                          memory=memory, timeline=timeline)
    if reco:
        table += ('\n(no summary record found — reconstructed from '
                  '%d individual records; registry-only counters and '
                  'gauges are not recoverable)' % len(records))
    return table


# ---------------------------------------------------------------------------
# multi-host merge (one JSONL per host, records stamped with 'host')
# ---------------------------------------------------------------------------

def split_hosts(record_lists):
    """Merge per-file record lists on the ``host`` field (records from
    a pre-cluster log without the stamp fall back to the file index).
    Files whose records share one host stamp (two processes both left
    MXTPU_HOST_ID at 0) collapse into one key — warn, so the silent
    keep-last-summary merge is visible."""
    by_host = {}
    hosts_per_file = []
    for i, recs in enumerate(record_lists):
        seen = set()
        # a supervisor log (gang.jsonl: restart/hang records only)
        # SHARES host stamps with the worker logs by design — its
        # records merge into each worker's view without tripping the
        # duplicate-stamp warning below, which is about two WORKER logs
        # left on the same MXTPU_HOST_ID
        sup_only = bool(recs) and all(r.get('type') in ('restart', 'hang')
                                      for r in recs)
        for r in recs:
            host = r.get('host', i)
            seen.add(host)
            by_host.setdefault(host, []).append(r)
        hosts_per_file.append(set() if sup_only else seen)
    nonempty = sum(1 for s in hosts_per_file if s)
    if len(by_host) < nonempty:
        sys.stderr.write(
            'telemetry_report: %d files merged into %d host(s) — '
            'multiple logs carry the same host stamp (set distinct '
            'MXTPU_HOST_ID per process); only the last summary record '
            'per host renders\n' % (nonempty, len(by_host)))
    return by_host


def _io_share(snapshot):
    """io.prefetch_wait share (%) of the driven loop time — the offline
    twin of telemetry.health.input_bound_pct, over a snapshot dict
    (same span families, shared constants: the two cannot drift)."""
    from mxnet_tpu.telemetry.health import (FUSED_FIT_LOOP_SPANS,
                                            EVAL_LOOP_SPANS)
    hists = snapshot.get('histograms', {})
    io = hists.get('io.prefetch_wait')
    if not io or not io.get('count'):
        return None
    denom = (hists.get('fit.batch') or {}).get('sum') or 0.0
    if not denom:
        for name in FUSED_FIT_LOOP_SPANS:
            denom += (hists.get(name) or {}).get('sum') or 0.0
    for name in EVAL_LOOP_SPANS:
        denom += (hists.get(name) or {}).get('sum') or 0.0
    if denom <= 0.0:
        return None
    return min(100.0, 100.0 * io['sum'] / denom)


def _step_ms(snapshot):
    """Best available per-step milliseconds for one host, normalized so
    hosts are commensurate: the fit.batch p50 (per-step median) when
    the per-batch loop ran, else the fused window's dispatch p50
    divided by its steps-per-call (one observation covers W steps),
    else the last health.step_time_ms sample (per-step, but
    last-write-wins — noisier)."""
    hists = snapshot.get('histograms', {})
    g = snapshot.get('gauges', {})
    h = hists.get('fit.batch')
    if h and h.get('count') and h.get('p50') is not None:
        return float(h['p50'])
    h = hists.get('fused_fit.dispatch')
    w = g.get('fused_fit.steps_per_call')
    if h and h.get('count') and h.get('p50') is not None and w:
        return float(h['p50']) / float(w)
    if g.get('health.step_time_ms') is not None:
        return float(g['health.step_time_ms'])
    return None


def render_hosts(by_host):
    """The per-host comparison table + straggler classification, then
    each host's full summary table — the offline twin of the live
    cluster aggregation (telemetry/cluster.py)."""
    from mxnet_tpu.telemetry.cluster import classify, _SPREAD_BALANCED_PCT
    rows = []
    for host in sorted(by_host):
        (snapshot, elapsed, programs, health, cluster, roof, _led,
         good, _mem, _tl, reco) = _summary_parts(by_host[host])
        steps = snapshot.get('counters', {}).get('fit.steps')
        if steps is None:
            steps = (snapshot.get('histograms', {})
                     .get('fit.batch') or {}).get('count')
        if steps is not None and float(steps).is_integer():
            steps = int(steps)   # registry counters are floats
        rows.append({'host': host, 'steps': steps,
                     'step_ms': _step_ms(snapshot),
                     'io_wait_pct': _io_share(snapshot),
                     # this host's roofline collective share — the
                     # offline classifier must see the same number the
                     # live sync vector carried, or the two verdicts
                     # diverge on communication_bound hosts
                     'comm_pct': ((roof or {}).get('comm') or {})
                     .get('pct_of_step'),
                     'goodput': (good or {}).get('goodput_pct'),
                     'nonfinite': int((health or {})
                                      .get('nonfinite_steps') or 0),
                     'records': by_host[host]})
    times = [r['step_ms'] for r in rows if r['step_ms'] is not None]
    slowest = None
    spread = None
    if times:
        import statistics
        slowest = max((r for r in rows if r['step_ms'] is not None),
                      key=lambda r: r['step_ms'])['host']
        # true median, matching cluster._publish's np.median — the
        # offline verdict must agree with the live one at the threshold
        med = statistics.median(times)
        spread = ((max(times) - min(times)) / med * 100.0) if med else 0.0
    lines = ['== per-host comparison (%d hosts) ==' % len(rows)]
    lines.append('  host    steps   step_ms   io_wait%  goodput%  '
                 'nonfinite  class')
    for r in rows:
        mark = '*' if (r['host'] == slowest and len(rows) > 1) else ''
        # no io-wait data = no classification; a confident
        # 'compute_bound' with a '-' io column would be fabricated
        cls = '-' if r['io_wait_pct'] is None \
            else classify(r['io_wait_pct'], comm_pct=r['comm_pct'])
        lines.append('  %-6s  %-6s  %-8s  %-8s  %-8s  %-9s  %s'
                     % ('%s%s' % (r['host'], mark),
                        '-' if r['steps'] is None else r['steps'],
                        '-' if r['step_ms'] is None
                        else '%.3f' % r['step_ms'],
                        '-' if r['io_wait_pct'] is None
                        else '%.1f' % r['io_wait_pct'],
                        '-' if r['goodput'] is None
                        else '%.1f' % r['goodput'],
                        r['nonfinite'], cls))
    if spread is not None and len(rows) > 1:
        if spread < _SPREAD_BALANCED_PCT:
            verdict = 'balanced (step-time spread %.1f%%)' % spread
        else:
            slow_row = next(r for r in rows if r['host'] == slowest)
            cls = 'unclassified (no io-wait data)' \
                if slow_row['io_wait_pct'] is None \
                else classify(slow_row['io_wait_pct'],
                              comm_pct=slow_row['comm_pct'])
            verdict = ('host %s straggles — %s (step-time spread %.1f%%)'
                       % (slowest, cls, spread))
        lines.append('  straggler: %s' % verdict)
    out = ['\n'.join(lines)]
    for r in rows:
        out.append('')
        out.append('== host %s ==' % r['host'])
        out.append(render(r['records']))
    return '\n'.join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='Render telemetry JSONL logs (MXTPU_TELEMETRY_PATH) '
                    'into the end-of-run summary table, offline. Multiple '
                    'paths (one per host) merge on the host field and add '
                    'a per-host comparison + straggler classification.')
    ap.add_argument('paths', nargs='+',
                    help='telemetry JSONL file(s) to render, or a gang '
                         'log directory holding h<i>.jsonl files')
    args = ap.parse_args(argv)
    paths = expand_paths(args.paths)
    if not paths:
        sys.stderr.write('telemetry_report: nothing to render\n')
        return 1
    record_lists = [load(p) for p in paths]
    if not any(record_lists):
        sys.stderr.write('telemetry_report: %s hold(s) no records\n'
                         % ', '.join(paths))
        return 1
    if len(record_lists) == 1:
        print(render(record_lists[0]))
        return 0
    print(render_hosts(split_hosts(record_lists)))
    return 0


if __name__ == '__main__':
    sys.exit(main())
