#!/usr/bin/env python3
"""im2rec — build RecordIO image packs from a .lst listing.

Reference: tools/im2rec.cc + tools/im2rec.py (list-file driven packer:
``index\\tlabel[\\tlabel...]\\trelative/path`` per line, images resized
and encoded into IRHeader-framed records, optional .idx for random
access).

TPU-native pipeline note: the output .rec is consumed by
ImageRecordIter / ImageDetRecordIter, which batch into dense arrays on
the host and feed the device whole batches — so this tool is also where
ragged detection labels get packed (--pack-label writes the
[header_width, object_width, objects...] label block).

Also supports --make-list to generate a .lst from an image directory.
"""
import argparse
import os
import random
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu.recordio import MXIndexedRecordIO, IRHeader, pack_img  # noqa: E402

_IMG_EXTS = {'.jpg', '.jpeg', '.png', '.bmp', '.npy'}


def make_list(args):
    """Reference im2rec.py make_list: scan a directory into .lst files."""
    entries = []
    for root, _, files in sorted(os.walk(args.root)):
        for fname in sorted(files):
            if os.path.splitext(fname)[1].lower() in _IMG_EXTS:
                entries.append(os.path.relpath(os.path.join(root, fname),
                                               args.root))
    # label = index of the containing directory, as in the reference
    dirs = sorted({os.path.dirname(e) for e in entries})
    dir_label = {d: i for i, d in enumerate(dirs)}
    if args.shuffle:
        random.seed(100)
        random.shuffle(entries)
    n_test = int(len(entries) * args.test_ratio)
    n_train = int(len(entries) * args.train_ratio)
    chunks = {'_test': entries[:n_test], '_train': entries[n_test:n_test + n_train]}
    if args.train_ratio + args.test_ratio < 1.0:
        chunks['_val'] = entries[n_test + n_train:]
    if args.train_ratio == 1.0 and args.test_ratio == 0.0:
        chunks = {'': entries}
    for suffix, chunk in chunks.items():
        if not chunk:
            continue
        with open(args.prefix + suffix + '.lst', 'w') as f:
            for i, e in enumerate(chunk):
                f.write('%d\t%d\t%s\n' % (i, dir_label[os.path.dirname(e)], e))


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split('\t')
            if len(parts) < 3:
                continue
            yield int(parts[0]), [float(x) for x in parts[1:-1]], parts[-1]


def load_image(path, args):
    """Load + resize/center-crop to the target edge (reference resize logic)."""
    if path.endswith('.npy'):
        img = np.load(path)
        if img.ndim == 2:
            img = img[None]
        elif img.ndim == 3 and img.shape[2] in (1, 3):
            img = img.transpose(2, 0, 1)
        return img.astype(np.uint8)
    from PIL import Image
    im = Image.open(path).convert('RGB')
    if args.resize > 0:
        w, h = im.size
        if w < h:
            nw, nh = args.resize, int(h * args.resize / w)
        else:
            nw, nh = int(w * args.resize / h), args.resize
        im = im.resize((nw, nh))
    if args.center_crop and args.resize > 0:
        w, h = im.size
        left = (w - args.resize) // 2
        top = (h - args.resize) // 2
        im = im.crop((left, top, left + args.resize, top + args.resize))
    return np.asarray(im).transpose(2, 0, 1)


def write_rec(args):
    prefix = os.path.splitext(args.prefix)[0]
    rec = MXIndexedRecordIO(prefix + '.idx', prefix + '.rec', 'w')
    n = 0
    for idx, labels, rel in read_list(args.lst):
        path = os.path.join(args.root, rel)
        try:
            img = load_image(path, args)
        except Exception as e:  # noqa: BLE001 — reference skips bad images
            print('skipping %s: %s' % (rel, e), file=sys.stderr)
            continue
        if args.pack_label:
            label = np.asarray(labels, dtype=np.float32)
        elif len(labels) == 1:
            label = labels[0]
        else:
            label = np.asarray(labels, dtype=np.float32)
        header = IRHeader(0, label, idx, 0)
        fmt = '.raw' if (args.encoding == 'raw' or path.endswith('.npy')) \
            else args.encoding
        if fmt != '.raw' and img.ndim == 3:
            img = img.transpose(1, 2, 0)  # PIL encoders take HWC
        rec.write_idx(idx, pack_img(header, img, quality=args.quality,
                                    img_fmt=fmt))
        n += 1
        if n % 1000 == 0:
            print('packed %d' % n)
    rec.close()
    print('wrote %d records to %s.rec' % (n, prefix))


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument('prefix', help='prefix for .lst/.rec/.idx files')
    p.add_argument('root', help='image root directory')
    p.add_argument('--make-list', action='store_true',
                   help='generate .lst instead of packing records')
    p.add_argument('--lst', default=None, help='list file (default prefix.lst)')
    p.add_argument('--resize', type=int, default=0,
                   help='resize shorter edge to this')
    p.add_argument('--center-crop', action='store_true')
    p.add_argument('--quality', type=int, default=95)
    p.add_argument('--encoding', default='.jpg',
                   choices=['.jpg', '.png', 'raw'])
    p.add_argument('--pack-label', action='store_true',
                   help='store the full multi-column label (detection .lst)')
    p.add_argument('--shuffle', action='store_true', default=True)
    p.add_argument('--no-shuffle', dest='shuffle', action='store_false')
    p.add_argument('--train-ratio', type=float, default=1.0)
    p.add_argument('--test-ratio', type=float, default=0.0)
    args = p.parse_args(argv)
    if args.lst is None:
        args.lst = args.prefix + '.lst' if not args.prefix.endswith('.lst') \
            else args.prefix
    if args.make_list:
        make_list(args)
    else:
        write_rec(args)


if __name__ == '__main__':
    main()
