"""Raw host->device upload bandwidth probe for the fed-fit bound.

The fed `ImageRecordIter -> Module.fit` bench (tools/fed_fit_bench.py)
must ship every uint8 source batch to the device, unlike the synthetic
bench whose data lives on-device. On a real TPU host that transfer
rides PCIe/DMA at GB/s; in this dev environment it crosses the axon
tunnel. This probe times nothing but `jax.device_put` of the exact
batch shape the fed bench uploads (B, S, S, 3) uint8, so the fed
number can be read against the transport's own ceiling: if
fed_img_s ~= probe_MBps / bytes_per_image, the framework streams at
line rate and the gap to the synthetic rate is the tunnel, not the
pipeline. Reference role: the in-process OMP feed of
src/io/iter_image_recordio_2.cc never crosses a network hop.

Prints ONE json line.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

BATCH = int(os.environ.get('MXTPU_BENCH_BATCH', 32))
SRC = int(os.environ.get('MXTPU_FED_SRC', 256))
REPS = int(os.environ.get('MXTPU_PROBE_REPS', 24))


def main():
    import jax
    dev = jax.devices()[0]
    rng = np.random.RandomState(0)
    # distinct buffers so no caching layer can dedupe the transfer
    batches = [rng.randint(0, 256, (BATCH, SRC, SRC, 3), np.uint8)
               for _ in range(REPS)]
    nbytes = batches[0].nbytes

    import jax.numpy as jnp

    def landed(devs):
        # host-fetch barrier over a value derived from EVERY buffer:
        # block_until_ready can return EARLY through the tunnel
        # (verify-skill note; the 2026-08-02 654 MB/s artifact was an
        # artifact of that). One barrier for the whole train, so the
        # per-fetch RTT is amortized and pure transfer time dominates
        s = sum(jnp.sum(a[:, -1, -1, :].astype(jnp.int32)) for a in devs)
        float(np.asarray(s))

    # warmup (backend init + any lazy transfer setup)
    landed([jax.device_put(batches[0], dev)])

    t0 = time.perf_counter()
    landed([jax.device_put(b, dev) for b in batches])
    dt = time.perf_counter() - t0

    mbps = REPS * nbytes / dt / 1e6
    img_s_ceiling = REPS * BATCH / dt
    out = {'metric': 'host_to_device_upload_bw', 'value': round(mbps, 2),
           'unit': 'MB/s', 'platform': dev.platform,
           'batch_bytes': nbytes, 'reps': REPS,
           'fed_img_s_ceiling': round(img_s_ceiling, 1),
           'shape': [BATCH, SRC, SRC, 3], 'dtype': 'uint8'}
    print(json.dumps(out))


if __name__ == '__main__':
    main()
