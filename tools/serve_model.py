#!/usr/bin/env python
"""Serve a saved checkpoint over HTTP with continuous batching.

The checkpoint -> endpoint path (docs/serving.md)::

    python tools/serve_model.py mymodel --epoch 3 --data-shape 3,224,224
    python tools/serve_model.py mymodel --epoch 3 --data-shape 10 \
        --port 8500 --max-batch 64 --max-wait-ms 3

Loads ``<prefix>-symbol.json`` + ``<prefix>-<epoch>.params``
(``Module.save_checkpoint`` artifacts) via ``Module.load``, binds for
inference, pre-compiles the bucket ladder (power-of-two batch shapes up
to --max-batch; warm instantly across restarts with
``MXTPU_COMPILE_CACHE`` set), and serves:

- ``POST /predict`` — JSON ``{"data": [[...], ...]}`` (or
  ``{"inputs": {...}}`` for multi-input graphs, or a raw .npy body);
  concurrent requests coalesce into shared padded device dispatches
  (queue -> coalesce -> dispatch -> split);
- ``GET /models`` / ``/healthz`` / ``/metrics`` — signature, probe,
  and the Prometheus ``serve.*`` family (latency p50/p99, queue depth,
  batch size, pad fraction, request/error counters).

Run with MXTPU_TELEMETRY=1 to light up the metrics; point
``tools/telemetry_watch.py`` at a telemetry endpoint (or this server's
/metrics via your scrape infra) to watch the serving line live.
"""
import argparse
import logging
import os
import signal
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _parse_shape(text):
    try:
        return tuple(int(d) for d in text.split(',') if d.strip())
    except ValueError:
        raise argparse.ArgumentTypeError(
            'shape must be comma-separated ints, e.g. 3,224,224')


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='Serve a Module checkpoint over HTTP with dynamic '
                    'batching over pre-compiled bucketed batch shapes '
                    '(docs/serving.md).')
    ap.add_argument('prefix', help='checkpoint prefix '
                    '(<prefix>-symbol.json, <prefix>-NNNN.params)')
    ap.add_argument('--epoch', type=int, default=0,
                    help='checkpoint epoch to load (default 0)')
    ap.add_argument('--data-shape', type=_parse_shape, required=True,
                    action='append', dest='data_shapes',
                    help='per-example input shape WITHOUT the batch dim, '
                         'e.g. 3,224,224 (repeat for multi-input graphs, '
                         'in --data-name order)')
    ap.add_argument('--data-name', action='append', dest='data_names',
                    help='input name(s), default "data"')
    ap.add_argument('--port', type=int, default=8500,
                    help='HTTP port (0 = OS-assigned ephemeral, printed '
                         'at startup; default 8500)')
    ap.add_argument('--max-batch', type=int, default=None,
                    help='largest batch bucket (default '
                         'MXTPU_SERVE_MAX_BATCH)')
    ap.add_argument('--max-wait-ms', type=float, default=None,
                    help='batcher coalescing deadline (default '
                         'MXTPU_SERVE_MAX_WAIT_MS)')
    ap.add_argument('--context', default='cpu', choices=['cpu', 'tpu'],
                    help='device to serve from (default cpu)')
    ap.add_argument('--no-warmup', action='store_true',
                    help='skip pre-compiling the bucket ladder (first '
                         'requests then pay the compiles)')
    args = ap.parse_args(argv)

    names = args.data_names or ['data']
    if len(names) != len(args.data_shapes):
        ap.error('--data-name count (%d) must match --data-shape count '
                 '(%d)' % (len(names), len(args.data_shapes)))

    logging.basicConfig(level=logging.INFO,
                        format='%(asctime)s %(message)s')
    import mxnet_tpu as mx
    from mxnet_tpu.serving import ServingEngine, DynamicBatcher
    from mxnet_tpu.serving.http import start_server

    ctx = mx.tpu() if args.context == 'tpu' else mx.cpu()
    engine = ServingEngine.from_checkpoint(
        args.prefix, args.epoch,
        data_shapes=list(zip(names, args.data_shapes)),
        context=ctx, max_batch=args.max_batch)
    if not args.no_warmup:
        engine.warmup()
    server = start_server(engine,
                          DynamicBatcher(engine,
                                         max_wait_ms=args.max_wait_ms),
                          port=args.port)
    print('serving %s on port %d (buckets %s)'
          % (engine.name, server.port, engine.buckets), flush=True)

    # an Event has no check-then-wait window: a SIGTERM landing at any
    # point sets it and wait() returns — never a signal consumed just
    # before a pause() that then blocks forever
    import threading
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == '__main__':
    sys.exit(main())
