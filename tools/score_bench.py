"""On-chip inference scoring tier (VERDICT r3 #4 / BASELINE.md table 1).

The reference's `benchmark_score.py` table (docs/how_to/perf.md:115-146)
scores AlexNet / VGG-16 / Inception-v3 / ResNet-50 / ResNet-152 at
batch 1 and 32. This tool scores the same model-zoo networks on the
TPU with the round-3 capture discipline (throwaway-subprocess probe,
host-fetch barrier, scan-fused repeats so the tunnel's per-dispatch
RTT cannot cap a 1-3 ms forward):

    python tools/score_bench.py                 # full table
    python tools/score_bench.py --models resnet50_v1 --batches 32

Forward-only inference in bfloat16 (the TPU inference dtype; the MXU
has no fp32 peak worth scoring against) on synthetic data via the
model zoo's hybridized graphs — the same `_GraphProgram` trace a user
gets from `net.hybridize()`. One JSON line per (model, batch), then a
summary line with the P100 baseline ratios.
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# reference table, P100 column (docs/how_to/perf.md:115-146)
P100 = {
    ('alexnet', 1): 624.84, ('alexnet', 32): 4883.77,
    ('vgg16', 1): 294.6, ('vgg16', 32): 854.4,
    ('inception-bn', 1): 139.82, ('inception-bn', 32): 1197.74,
    ('inceptionv3', 1): 80.17, ('inceptionv3', 32): 493.72,
    ('resnet50_v1', 1): 162.27, ('resnet50_v1', 32): 713.17,
    ('resnet152_v1', 1): 58.99, ('resnet152_v1', 32): 294.17,
}
# pretrained-model speed table, single K80 batch 32
# (example/image-classification/README.md:147-157)
K80_PRETRAINED = {
    ('inception-bn', 32): 152.0,
    ('resnet18_v1', 32): 185.0, ('resnet34_v1', 32): 172.0,
    ('resnet50_v1', 32): 109.0, ('resnet101_v1', 32): 78.0,
    ('resnet152_v1', 32): 57.0,
}
DEFAULT_MODELS = ['alexnet', 'vgg16', 'inception-bn', 'inceptionv3',
                  'resnet18_v1', 'resnet34_v1', 'resnet50_v1',
                  'resnet101_v1', 'resnet152_v1']


def _log(msg):
    print('[score] ' + msg, file=sys.stderr, flush=True)


def _probe():
    import subprocess
    code = 'import jax; print("PROBE_OK", jax.devices()[0].platform)'
    try:
        out = subprocess.run([sys.executable, '-c', code], timeout=240,
                             capture_output=True, text=True).stdout
    except Exception as e:  # noqa: BLE001
        _log('probe failed: %s' % e)
        return False
    return 'PROBE_OK' in (out or '')


def build_forward(model, batch):
    """(compiled_chain, reps, flops_per_fwd) for a scan of ``reps``
    data-chained bf16 forwards of the zoo model."""
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.executor import _GraphProgram

    image = 299 if model == 'inceptionv3' else 224
    shape = (batch, 3, image, image)
    if model == 'inception-bn':
        # symbol-defined network (examples/image-classification/symbols)
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            'examples', 'image-classification'))
        from symbols.inception_bn import get_symbol
        # SoftmaxOutput's label input is unused in inference mode
        sym = get_symbol(num_classes=1000,
                         image_shape='3,%d,%d' % (image, image))
    else:
        net = vision.get_model(model, classes=1000)
        net.initialize(mx.init.Xavier())
        net.hybridize()
        _, sym = net._get_graph(
            type('P', (), {'shape': shape, 'context': None})())
    prog = _GraphProgram(sym)
    arg_shapes, _, aux_shapes = sym.infer_shape(data=shape)
    runner = prog.make_runner()
    rng = np.random.RandomState(0)

    def init(name, s):
        if 'gamma' in name or 'var' in name:
            return np.ones(s, np.float32)
        if 'beta' in name or 'bias' in name or 'mean' in name:
            return np.zeros(s, np.float32)
        fan = int(np.prod(s[1:])) if len(s) > 1 else s[0]
        return (rng.standard_normal(s) * (2.0 / max(1, fan)) ** 0.5) \
            .astype(np.float32)

    data_idx = prog.arg_names.index('data')
    args = [jnp.asarray(init(n, s)).astype(jnp.bfloat16)
            for n, s in zip(prog.arg_names, arg_shapes)]
    aux = tuple(jnp.asarray(init(n, s)).astype(jnp.bfloat16)
                for n, s in zip(prog.aux_names, aux_shapes))
    x = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    key = jax.random.PRNGKey(0)

    # reps sized so one chain call is ~1-2 s of device time (ResNet-50
    # b32 measures ~6 ms/forward; scale by batch and image area)
    est_ms = 6.0 * batch / 32.0 * (image / 224.0) ** 2
    reps = int(np.clip(1500.0 / est_ms, 16, 512))

    def chain(args_t, aux_t, x):
        def body(c, _):
            xx = c
            full = list(args_t)
            full[data_idx] = xx
            outs, _ = runner(tuple(full), aux_t, key, False)
            # 1e-30 tap: numerically identity, but keeps iterations
            # data-dependent so XLA cannot CSE/hoist the forward
            tap = jnp.sum(outs[0].astype(jnp.float32)) * 1e-30
            return (xx * (1 + tap).astype(xx.dtype)), ()
        c, _ = jax.lax.scan(body, x, None, length=reps)
        full = list(args_t)
        full[data_idx] = c
        outs, _ = runner(tuple(full), aux_t, key, False)
        return jnp.sum(outs[0].astype(jnp.float32))

    jfn = jax.jit(chain)
    lowered = jfn.lower(tuple(args), aux, x)
    compiled = lowered.compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    # XLA cost analysis counts a scan body ONCE regardless of trip
    # count (verified in bench.py): total = 1 body + 1 final forward
    flops = float(cost.get('flops', 0.0)) / 2.0
    try:
        ma = compiled.memory_analysis()
        if isinstance(ma, (list, tuple)):
            ma = ma[0]
        n_param = sum(int(np.prod(s)) for n, s in
                      zip(prog.arg_names, arg_shapes)
                      if n not in ('data', 'softmax_label'))
        n_param += sum(int(np.prod(s)) for s in aux_shapes)
        mem = {'xla_temp_bytes': int(ma.temp_size_in_bytes),
               'param_bytes': 2 * n_param}   # bf16 resident weights
    except Exception:  # noqa: BLE001
        mem = {}
    return compiled, tuple(args), aux, x, reps, flops, mem


def score(model, batch, peak):
    import jax
    t = time.perf_counter()
    compiled, args, aux, x, reps, flops, mem = build_forward(model, batch)
    _log('%s b%d: compile %.1fs (reps=%d)'
         % (model, batch, time.perf_counter() - t, reps))
    float(np.asarray(compiled(args, aux, x)))   # warmup + barrier
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(np.asarray(compiled(args, aux, x)))
        ts.append(time.perf_counter() - t0)
    dt = sorted(ts)[1] / (reps + 1)
    ips = batch / dt
    mfu = flops / dt / peak if peak else None
    row = {'metric': 'benchmark_score', 'model': model, 'batch': batch,
           'value': round(ips, 2), 'unit': 'images/sec',
           'dtype': 'bfloat16'}
    if (model, batch) in P100:
        row['vs_p100'] = round(ips / P100[(model, batch)], 2)
    if (model, batch) in K80_PRETRAINED:
        row['vs_k80_pretrained'] = round(
            ips / K80_PRETRAINED[(model, batch)], 2)
    if mfu is not None:
        row['mfu'] = round(mfu, 4)
    row.update(mem)
    print(json.dumps(row), flush=True)
    _log('%s b%d: %.1f img/s (%.2fx P100)'
         % (model, batch, ips, row.get('vs_p100', 0)))
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--models', default=','.join(DEFAULT_MODELS))
    ap.add_argument('--batches', default='1,32')
    args = ap.parse_args()
    _log('probing backend in throwaway subprocess...')
    if not _probe():
        _log('chip unreachable')
        sys.exit(2)
    import jax
    from bench import _peak_flops   # shared device-kind -> peak table
    dev = jax.devices()[0]
    peak, _kind = _peak_flops(dev)
    _log('backend: %s' % dev)
    rows = []
    for model in args.models.split(','):
        for b in (int(x) for x in args.batches.split(',')):
            try:
                rows.append(score(model, b, peak))
            except Exception as e:  # noqa: BLE001
                _log('%s b%d FAILED: %s' % (model, b, e))
    ok = [r for r in rows if 'vs_p100' in r]
    k80 = [r for r in rows if 'vs_k80_pretrained' in r]
    summary = {'metric': 'benchmark_score_summary',
               'value': round(min((r['vs_p100'] for r in ok), default=0.0),
                              2),
               'unit': 'min_vs_p100',
               'all_above_p100': bool(ok) and all(
                   r['vs_p100'] >= 1.0 for r in ok),
               'all_above_k80_pretrained': bool(k80) and all(
                   r['vs_k80_pretrained'] >= 1.0 for r in k80),
               'rows': rows}
    print(json.dumps(summary), flush=True)


if __name__ == '__main__':
    main()
