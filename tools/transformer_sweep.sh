#!/bin/bash
# Transformer MFU/long-context sweep (round 4): probes whether larger
# d_model, longer sequences, or spc=64 move the 56.1% round-3 MFU, and
# banks a long-context (seq 8192/16384, flash-attention Pallas) on-chip
# artifact. One TPU process at a time — run only when the chip is free.
set -u
cd "$(dirname "$0")/.."
OUT=docs/tpu_artifacts
mkdir -p "$OUT"
STAMP=$(date -u +%Y%m%dT%H%M%SZ)

run () {  # run <tag> <env...>
  tag=$1; shift
  echo "== transformer $tag =="
  env "$@" MXTPU_BENCH_MODEL=transformer MXTPU_BENCH_BUDGET=420 \
    timeout 600 python bench.py \
    > "$OUT/bench_tf_${tag}_$STAMP.json" 2> "$OUT/bench_tf_${tag}_$STAMP.log"
  echo "rc=$?"; tail -1 "$OUT/bench_tf_${tag}_$STAMP.json"
}

run d2048L8   MXTPU_BENCH_DMODEL=2048 MXTPU_BENCH_BATCH=4
run spc64     MXTPU_BENCH_STEPS_PER_CALL=64
run seq2048   MXTPU_BENCH_SEQ=2048 MXTPU_BENCH_BATCH=4
run seq8192   MXTPU_BENCH_SEQ=8192 MXTPU_BENCH_BATCH=1
run seq16384  MXTPU_BENCH_SEQ=16384 MXTPU_BENCH_BATCH=1 \
              MXTPU_BENCH_STEPS_PER_CALL=8
echo "== done =="
