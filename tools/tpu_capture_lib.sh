# Shared helpers for the chained round-5 capture scripts. Source me:
#   . "$(dirname "$0")/tpu_capture_lib.sh"
#
# Discipline (memory: a second prober deepens a tunnel wedge):
# - exactly ONE process probes the chip at a time; a chained script
#   must HARD-FAIL (exit) if its predecessor never finishes, never
#   fall through into concurrent probing/benching.

# wait_for_predecessor <logfile> <done-regex> <proc-pattern>
# Returns 0 when the predecessor finished (sentinel in its log or its
# process gone); exits 1 if it is still alive when patience runs out.
wait_for_predecessor() {
  local log=$1 done_re=$2 pat=$3
  for i in $(seq 1 140); do   # ~14 h patience
    if grep -qE "$done_re" "$log" 2>/dev/null; then
      echo "predecessor finished (sentinel)"
      return 0
    fi
    if ! pgrep -f "$pat" > /dev/null 2>&1; then
      echo "predecessor process gone"
      return 0
    fi
    sleep 360
  done
  echo "predecessor still running after patience window; NOT probing" \
       "concurrently — giving up"
  exit 1
}

probe_until_healthy() {
  for i in $(seq 1 40); do
    echo "$(date -u +%H:%M:%S) probe $i"
    if timeout 240 python -c 'import jax; assert any(d.platform=="tpu" for d in jax.devices())' 2>/dev/null; then
      echo "$(date -u +%H:%M:%S) chip healthy"
      return 0
    fi
    sleep 480
  done
  return 1
}
