#!/usr/bin/env python
"""Watch a live run: a top-style dashboard over the telemetry plane.

Polls the live endpoint a run exposes with ``MXTPU_TELEMETRY=1
MXTPU_TELEMETRY_PORT=<p>`` (telemetry/serve.py) — or tails a JSONL log
when given a file path — and renders throughput, MFU, run health and
the per-host cluster spread, refreshing in place::

    python tools/telemetry_watch.py http://tpu-host:9100
    python tools/telemetry_watch.py telemetry.jsonl
    python tools/telemetry_watch.py http://tpu-host:9100 --interval 5
    python tools/telemetry_watch.py http://tpu-host:9100 --once   # one frame

The HTTP mode reads ``/summary`` (the registry snapshot + health +
cluster as JSON); the file mode reuses tools/telemetry_report.py's
loader, so a crashed run's partial log renders too.
"""
import argparse
import json
import os
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
_TOOLS = os.path.dirname(os.path.abspath(__file__))
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

_CLEAR = '\x1b[2J\x1b[H'   # clear screen + home (refresh in place)


def fetch(source):
    """One dashboard input dict (the /summary JSON shape) from an HTTP
    base URL or a JSONL path."""
    if source.startswith(('http://', 'https://')):
        url = source.rstrip('/') + '/summary'
        with urllib.request.urlopen(url, timeout=5) as r:
            return json.loads(r.read().decode('utf-8'))
    import telemetry_report
    records = telemetry_report.load(source)
    summaries = [r for r in records if r.get('type') == 'summary']
    clus = [r for r in records if r.get('type') == 'cluster']
    mems = [r for r in records if r.get('type') == 'memory']
    last_mem = ({k: v for k, v in mems[-1].items()
                 if k not in ('type', 't', 'host')} if mems else None)
    tls = [r for r in records if r.get('type') == 'timeline']
    last_tl = ({k: v for k, v in tls[-1].items()
                if k not in ('type', 't', 'host')} if tls else None)
    if summaries:
        s = summaries[-1]
        return {'elapsed_s': s.get('elapsed_s'),
                'host': s.get('host'),
                'snapshot': s.get('snapshot') or {},
                'programs': s.get('programs'),
                'health': s.get('health'),
                'cluster': s.get('cluster')
                or (clus[-1] if clus else None),
                'memory': s.get('memory') or last_mem,
                'timeline': s.get('timeline') or last_tl,
                'ledger': s.get('ledger')
                or telemetry_report._reconstruct_ledger(records),
                'goodput': s.get('goodput')
                or telemetry_report._reconstruct_goodput(
                    records, s.get('snapshot') or {}, s.get('elapsed_s'),
                    s.get('roofline'),
                    s.get('ledger')
                    or telemetry_report._reconstruct_ledger(records))}
    snapshot, elapsed, programs, health = telemetry_report._reconstruct(
        records)
    led = telemetry_report._reconstruct_ledger(records)
    roofs = [r for r in records if r.get('type') == 'roofline']
    return {'elapsed_s': elapsed, 'host': None, 'snapshot': snapshot,
            'programs': programs, 'health': health,
            'cluster': clus[-1] if clus else None,
            'memory': last_mem,
            'timeline': last_tl,
            'ledger': led,
            'goodput': telemetry_report._reconstruct_goodput(
                records, snapshot, elapsed,
                roofs[-1] if roofs else None, led)}


def _fmt(v, suffix=''):
    if v is None:
        return '-'
    if isinstance(v, float):
        return ('%.3g' % v) + suffix
    return str(v) + suffix


_SPARK = '▁▂▃▄▅▆▇█'


def _sparkline(values):
    """Unicode block sparkline of a numeric series (min..max scaled;
    a flat series renders flat-low)."""
    vals = [float(v) for v in values]
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK[0] * len(vals)
    return ''.join(_SPARK[min(len(_SPARK) - 1,
                              int((v - lo) / (hi - lo)
                                  * (len(_SPARK) - 1) + 0.5))]
                   for v in vals)


def render(summary, steps_per_s=None, reqs_per_s=None):
    """The dashboard frame for one /summary dict, as a list of lines
    (pure — tested offline). ``steps_per_s`` / ``reqs_per_s`` are the
    poll-to-poll step and serving-request rates the caller measured."""
    snap = summary.get('snapshot') or {}
    c = snap.get('counters', {})
    g = snap.get('gauges', {})
    h = snap.get('histograms', {})
    lines = []
    head = 'mxnet_tpu live telemetry'
    if summary.get('host') is not None:
        head += ' — host %s' % summary['host']
    if summary.get('elapsed_s'):
        head += ' — up %.0fs' % summary['elapsed_s']
    lines.append(head)
    lines.append('')
    steps = c.get('fit.steps')
    rate_bits = []
    if steps is not None:
        rate_bits.append('steps %d' % steps)
    if steps_per_s is not None:
        rate_bits.append('%.2f steps/s' % steps_per_s)
    sps = g.get('speedometer.samples_per_sec') or g.get('eval_samples_per_sec')
    if sps is not None:
        rate_bits.append('%s samples/s' % _fmt(float(sps)))
    lines.append('  throughput   %s' % (', '.join(rate_bits) or '-'))
    if g.get('xla.mfu') is not None:
        lines.append('  mfu          %.1f%%' % (100.0 * float(g['xla.mfu'])))
    fb = h.get('fit.batch')
    if fb and fb.get('count'):
        lines.append('  step_time    p50 %s ms  p95 %s ms'
                     % (_fmt(fb.get('p50')), _fmt(fb.get('p95'))))
    else:
        # fused loop: the dispatch histogram is per-WINDOW (W steps);
        # normalize so the line reads per-step like the cluster rows
        fd = h.get('fused_fit.dispatch')
        w = g.get('fused_fit.steps_per_call')
        if fd and fd.get('count') and fd.get('p50') is not None and w:
            lines.append('  step_time    ~%s ms/step '
                         '(window dispatch p50 / %d)'
                         % (_fmt(float(fd['p50']) / float(w)), int(w)))
    if g.get('fit.input_bound_pct') is not None:
        lines.append('  io_wait      %s%% of loop time'
                     % _fmt(float(g['fit.input_bound_pct'])))
    # goodput line (telemetry/goodput.py): the productive share of
    # wall-clock so far, plus the biggest badput bucket by name — the
    # live twin of the end-of-run "where the time went" block
    good = summary.get('goodput') or {}
    if good.get('goodput_pct') is not None:
        bits = ['%.1f%% productive' % float(good['goodput_pct'])]
        top = good.get('badput_top')
        if top:
            secs = (good.get('buckets') or {}).get(top)
            bits.append('top badput %s%s'
                        % (top, ' (%.1fs)' % secs
                           if isinstance(secs, (int, float)) else ''))
        if good.get('rework_steps'):
            bits.append('%d steps reworked' % int(good['rework_steps']))
        if good.get('job_goodput_pct') is not None:
            bits.append('job %.1f%% across restarts'
                        % float(good['job_goodput_pct']))
        lines.append('  goodput      %s' % ', '.join(bits))
    if g.get('xla.bytes_in_use') is not None:
        lines.append('  device_mem   %.1f MiB live, %.1f MiB peak'
                     % (g['xla.bytes_in_use'] / 2.0**20,
                        (g.get('xla.peak_bytes_in_use')
                         or g['xla.bytes_in_use']) / 2.0**20))
    # memory plane (MXTPU_MEMORY): headroom + steps-to-OOM forecast +
    # the worst layer by attributed peak bytes, from the mem.* gauges
    # or (JSONL mode) the last memory record / summary fold
    mem = summary.get('memory') or {}
    head = g.get('mem.headroom_pct', mem.get('headroom_pct'))
    oom = g.get('mem.steps_to_oom', mem.get('steps_to_oom'))
    worst = g.get('mem.worst_layer', mem.get('worst_layer'))
    ring = g.get('serve.ring_bytes')
    if head is not None or oom is not None or worst is not None \
            or ring is not None:
        bits = []
        if head is not None:
            bits.append('headroom %s%%' % _fmt(float(head)))
        if oom is not None:
            bits.append('~%d steps to OOM' % int(oom))
        if worst is not None:
            wb = g.get('mem.worst_layer_bytes', mem.get('worst_layer_bytes'))
            bits.append('worst layer %s%s'
                        % (worst, ' (%.1f MiB)' % (float(wb) / 2.0**20)
                           if wb is not None else ''))
        if ring is not None:
            bits.append('serve ring %.1f MiB' % (float(ring) / 2.0**20))
        if g.get('mem.pressure', 1 if mem.get('pressure') else None):
            bits.append('MEM_PRESSURE')
        lines.append('  memory       %s' % ', '.join(bits))
    # step timeline (MXTPU_TIMELINE): who gates the gang step and by
    # how much — from the timeline.* gauges or (JSONL mode) the last
    # timeline record / summary fold
    tl = summary.get('timeline') or {}
    crit_host = g.get('timeline.critical_host', tl.get('critical_host'))
    crit_phase = g.get('timeline.critical_phase', tl.get('critical_phase'))
    if crit_host is not None or crit_phase is not None:
        bits = ['critical host %s %s'
                % ('-' if crit_host is None else int(crit_host),
                   crit_phase or '-')]
        skew = g.get('timeline.skew_ms', tl.get('skew_ms'))
        if skew is not None:
            bits.append('skew %s ms/step' % _fmt(float(skew)))
        gs = g.get('timeline.gang_step_ms', tl.get('gang_step_ms'))
        if gs is not None:
            bits.append('gang step %s ms' % _fmt(float(gs)))
        lines.append('  timeline     %s' % ', '.join(bits))
    if g.get('update.opt_state_bytes_per_device') is not None:
        # sharded weight update (MXTPU_SHARDED_UPDATE): whether the
        # ZeRO layout is engaged and what the optimizer state costs
        # per device. The comm share is the STEP's whole collective
        # share (roofline accounting — grad sync + the update's
        # reduce-scatter/all-gather + any tp/pp traffic), labeled as
        # such: the update-only split lives in bench's
        # update_comm_bytes
        bits = ['%.1f MiB/device'
                % (g['update.opt_state_bytes_per_device'] / 2.0**20),
                'sharded' if g.get('update.sharded')
                else 'replicated']
        if g.get('update.sharded') and g.get('update.dp'):
            bits[-1] += ' dp=%d' % int(g['update.dp'])
        if g.get('roofline.comm_pct_of_step') is not None:
            bits.append('step collectives %s%%'
                        % _fmt(float(g['roofline.comm_pct_of_step'])))
        lines.append('  opt_state    %s' % ', '.join(bits))
    # quantized gradient collectives (MXTPU_GRAD_COMPRESS): bytes per
    # sync step + ratio + mode, with the provenance spelled out —
    # 'measured' is real kvstore wire traffic, 'modeled' is the SPMD
    # window's arithmetic over the leaf layout
    if g.get('comm.bytes_on_wire_per_step') is not None:
        bits = ['%.2f MiB/step'
                % (float(g['comm.bytes_on_wire_per_step']) / 2.0**20)]
        if g.get('comm.compression_ratio') is not None:
            bits.append('%sx compressed'
                        % _fmt(float(g['comm.compression_ratio'])))
        if g.get('comm.mode'):
            bits.append('mode %s' % g['comm.mode'])
        if g.get('comm.bytes_src'):
            bits.append('(%s)' % g['comm.bytes_src'])
        lines.append('  comm         %s' % ', '.join(bits))
    # per-layer training dynamics (MXTPU_DYNAMICS): the layer changing
    # fastest relative to its size + the deadest output, straight from
    # the decimated dynamics.* gauges
    if g.get('dynamics.worst_update_ratio') is not None \
            or g.get('dynamics.dead_frac_max') is not None:
        bits = []
        if g.get('dynamics.worst_update_ratio') is not None:
            bits.append('worst %s dw/w %s'
                        % (g.get('dynamics.worst_layer') or '?',
                           _fmt(float(g['dynamics.worst_update_ratio']))))
        if g.get('dynamics.dead_frac_max') is not None:
            bits.append('dead %.0f%%'
                        % (100.0 * float(g['dynamics.dead_frac_max'])))
        if c.get('dynamics.layer_incidents'):
            n = int(c['dynamics.layer_incidents'])
            bits.append('%d layer incident%s' % (n,
                                                 's' if n != 1 else ''))
        lines.append('  dynamics     %s' % ', '.join(bits))
    # loss sparkline from the run ledger's recent scalars (non-finite
    # points — a diverged run's NaNs — are dropped from the scale)
    import math as _math
    led = summary.get('ledger') or {}
    recent = [p.get('loss') for p in (led.get('recent') or [])
              if isinstance(p.get('loss'), (int, float))
              and _math.isfinite(p['loss'])]
    if recent:
        lines.append('  loss         %s %s (last %d scalars)'
                     % (_fmt(float(recent[-1])), _sparkline(recent),
                        len(recent)))
    if c.get('serve.requests'):
        # serving plane (mxnet_tpu/serving): request rate + latency
        # percentiles + queue/batch state whenever serve.* metrics exist
        bits = ['%d reqs' % int(c['serve.requests'])]
        if reqs_per_s is not None:
            bits.append('%.2f req/s' % reqs_per_s)
        lat = h.get('serve.request_latency') or {}
        p99 = g.get('serve.request_latency_p99_ms')
        if lat.get('p50') is not None:
            bits.append('latency p50 %s ms%s'
                        % (_fmt(lat['p50']),
                           ' / p99 %s ms' % _fmt(float(p99))
                           if p99 is not None else ''))
        if g.get('serve.queue_depth') is not None:
            bits.append('queue %d' % int(g['serve.queue_depth']))
        if g.get('serve.batch_size_p50') is not None:
            bits.append('batch p50 %d' % int(g['serve.batch_size_p50']))
        if g.get('serve.pad_fraction') is not None:
            bits.append('pad %.0f%%' % (100.0
                                        * float(g['serve.pad_fraction'])))
        if c.get('serve.errors'):
            bits.append('%d errors' % int(c['serve.errors']))
        lines.append('  serving      %s' % ', '.join(bits))
        # per-stage latency breakdown (the tracing plane's histograms):
        # where a request's time goes — queue wait vs pad vs the
        # device round (dispatch + blocking fetch)
        qw = (h.get('serve.queue_wait') or {}).get('p50')
        pad = (h.get('serve.pad') or {}).get('p50')
        disp = (h.get('serve.dispatch') or {}).get('p50')
        fetch = (h.get('serve.fetch') or {}).get('p50')
        if qw is not None or pad is not None or disp is not None:
            comp = None
            if disp is not None or fetch is not None:
                comp = float(disp or 0.0) + float(fetch or 0.0)
            lines.append('  stages       queue p50 %s ms, pad p50 %s '
                         'ms, compute p50 %s ms (dispatch+fetch)'
                         % (_fmt(qw), _fmt(pad), _fmt(comp)))
    # SLO plane (telemetry/slo.py): objective, burn, budget — from the
    # slo.* gauges (HTTP and JSONL modes both carry them) or the
    # /summary payload's slo snapshot
    slo = summary.get('slo') or {}
    slo_lat = g.get('slo.latency_objective_ms',
                    slo.get('latency_objective_ms'))
    slo_budget = g.get('slo.error_budget_pct', slo.get('error_budget_pct'))
    if slo_lat is not None or slo_budget is not None:
        bits = []
        if slo_lat is not None:
            bits.append('latency obj %s ms' % _fmt(float(slo_lat)))
        if slo_budget is not None:
            bits.append('err budget %s%%' % _fmt(float(slo_budget)))
        burn = g.get('slo.burn_rate', slo.get('burn_rate'))
        if burn is not None:
            bits.append('burn %sx' % _fmt(float(burn)))
        remaining = g.get('slo.budget_remaining_pct',
                          slo.get('budget_remaining_pct'))
        if remaining is not None:
            bits.append('budget left %s%%' % _fmt(float(remaining)))
        if g.get('slo.degraded') or slo.get('degraded'):
            bits.append('DEGRADED')
        lines.append('  slo          %s' % ', '.join(bits))
    hs = summary.get('health')
    # hang / restart / elastic events render on the health line even
    # when the sentinel plane (MXTPU_HEALTH) is off — they live in
    # plain counters/gauges, so both the HTTP and JSONL modes see them
    restarts = int(c.get('health.restarts')
                   or (hs or {}).get('restarts') or 0)
    hangs = int(c.get('watchdog.hangs') or (hs or {}).get('hangs') or 0)
    shift = g.get('cluster.elastic_shift')
    if hs is not None or restarts or hangs or shift:
        bad = int((hs or {}).get('nonfinite_steps') or 0)
        status = 'ok' if not bad else 'DEGRADED (%d non-finite steps)' % bad
        bits = [status]
        if hangs:
            bits.append('%d hang%s' % (hangs, 's' if hangs != 1 else ''))
        if restarts:
            bits.append('%d restart%s' % (restarts,
                                          's' if restarts != 1 else ''))
        if shift:
            bits.append('shard shift %d' % int(shift))
        lines.append('  health       %s' % ', '.join(bits))
        last = (hs or {}).get('last_anomaly')
        if last:
            lines.append('  last_anomaly %s=%s (baseline %s)'
                         % (last.get('detector', '?'),
                            _fmt(last.get('value')),
                            _fmt(last.get('baseline'))))
    clus = summary.get('cluster')
    if clus:
        lines.append('')
        lines.append('  cluster (%s hosts, spread %s%%, straggler: %s)'
                     % (clus.get('hosts'), _fmt(clus.get('spread_pct')),
                        clus.get('straggler', '-')))
        lines.append('    host   step_ms    io_wait%   dispatch_ms')
        slow = clus.get('slowest_host')
        per = clus.get('per_host') or []
        for r in per:
            mark = '*' if (r.get('host') == slow and len(per) > 1) else ''
            lines.append('    %-5s  %-9s  %-9s  %s'
                         % ('%s%s' % (r.get('host'), mark),
                            _fmt(r.get('step_time_ms')),
                            _fmt(r.get('io_wait_pct')),
                            _fmt(r.get('dispatch_ms'))))
    return lines


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='Live top-style view of a telemetry endpoint '
                    '(http://host:MXTPU_TELEMETRY_PORT) or JSONL log.')
    ap.add_argument('source', help='endpoint base URL or JSONL path')
    ap.add_argument('--interval', type=float, default=2.0,
                    help='poll interval in seconds (default 2)')
    ap.add_argument('--once', action='store_true',
                    help='render one frame and exit (no screen clear)')
    args = ap.parse_args(argv)
    prev_steps = prev_reqs = prev_t = None
    while True:
        try:
            summary = fetch(args.source)
        except Exception as e:  # noqa: BLE001 — endpoint racing startup
            sys.stderr.write('telemetry_watch: %s\n' % e)
            if args.once:
                return 1
            time.sleep(args.interval)
            continue
        now = time.time()
        counters = (summary.get('snapshot') or {}).get('counters', {})
        steps = counters.get('fit.steps')
        reqs = counters.get('serve.requests')
        rate = req_rate = None
        if None not in (steps, prev_steps, prev_t) and now > prev_t:
            rate = max(0.0, (steps - prev_steps) / (now - prev_t))
        if None not in (reqs, prev_reqs, prev_t) and now > prev_t:
            req_rate = max(0.0, (reqs - prev_reqs) / (now - prev_t))
        prev_steps, prev_reqs, prev_t = steps, reqs, now
        frame = '\n'.join(render(summary, steps_per_s=rate,
                                 reqs_per_s=req_rate))
        if args.once:
            print(frame)
            return 0
        sys.stdout.write(_CLEAR + frame + '\n')
        sys.stdout.flush()
        time.sleep(args.interval)


if __name__ == '__main__':
    sys.exit(main())
