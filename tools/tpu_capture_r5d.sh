#!/bin/bash
# Chained round-5 capture, part D: transport-bound evidence for the
# fed-fit number. The 2026-08-02 fed_modulefit artifact measured 49.8
# img/s — suspiciously equal to ~10 MB/s of uint8 source upload. This
# banks the raw `jax.device_put` bandwidth of the exact batch shape so
# the fed rate can be read against the tunnel's own ceiling.
#
# Launch detached:
#   setsid nohup bash tools/tpu_capture_r5d.sh > /tmp/capture_r5d.log 2>&1 < /dev/null &
set -u
cd "$(dirname "$0")/.."
. tools/tpu_capture_lib.sh
OUT=docs/tpu_artifacts
mkdir -p "$OUT"
STAMP=$(date -u +%Y%m%dT%H%M%SZ)
echo "R5D CAPTURE STAMP=$STAMP"

wait_for_predecessor /tmp/capture_r5c.log \
  'R5C CAPTURE ALL DONE|gave up before' 'tools/tpu_capture_r5c\.sh'

probe_until_healthy || { echo "gave up before upload probe"; exit 1; }
echo "== upload bandwidth probe (fed batch shape) =="
timeout 600 python tools/upload_bw_probe.py \
  > "$OUT/upload_bw_$STAMP.json" 2> "$OUT/upload_bw_$STAMP.log"
echo "rc=$?"; tail -1 "$OUT/upload_bw_$STAMP.json"

echo "== R5D CAPTURE ALL DONE =="
