#!/usr/bin/env python
"""Compare two (or more) runs by their telemetry ledgers and gate on
training-dynamics regression.

Two runs happened — did the second one regress, and which layer is
why? Each run's telemetry JSONL (MXTPU_TELEMETRY_PATH, with
``MXTPU_SCALARS_EVERY`` banking the `scalars` timeseries and
``MXTPU_DYNAMICS`` the per-layer `dynamics` records) is a complete
ledger: manifest, loss curve, step times, per-layer dynamics. This
tool diffs them with the same verdict/exit-code discipline as
``tools/bench_diff.py``::

    python tools/run_compare.py baseline.jsonl candidate.jsonl

Compared, candidate vs the FIRST path (the baseline):

- ``loss_at_step``   — the loss at the last step both runs banked;
  higher is a regression (default tolerance 5%)
- ``final_loss``     — each run's last banked loss (same direction)
- ``time_to_loss``   — seconds to first reach the target loss
  (``--target-loss``, default: the baseline's final loss); slower is
  a regression (default 20%); a candidate that trained at least as
  many steps but never got there is a regression outright
- ``step_time_ms``   — median wall time per step between scalar
  records; higher is a regression (default 10%)

A candidate whose loss curve goes non-finite (or that recorded
named-layer ``dynamics`` incidents) while the baseline stayed clean is
DIVERGED — exit 1 regardless of tolerances. Improvements never fail;
a metric missing on either side renders as a skip with a trailing
note, never a silent pass. When both runs carry per-layer `dynamics`
records, layers whose update ratio or gradient norm drifted past
``--layer-tol-pct`` are listed and the worst one is named in the
verdict line — the "this run regressed and layer fc2 is why" loop.

Manifest differences (flags, jax version, device) print first: the
config diff is usually the explanation.
"""
import argparse
import math
import os
import statistics
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
_TOOLS = os.path.dirname(os.path.abspath(__file__))
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

_DEF_TOL = {'loss_at_step': 5.0, 'final_loss': 5.0,
            'time_to_loss': 20.0, 'step_time_ms': 10.0}
# every compared metric regresses UPWARD (loss, seconds, ms)
_ORDER = ('loss_at_step', 'final_loss', 'time_to_loss', 'step_time_ms')


def _finite(v):
    return v is not None and isinstance(v, (int, float)) \
        and math.isfinite(v)


class Run:
    """One run's ledger, extracted from its telemetry JSONL."""

    def __init__(self, path, records):
        self.path = path
        self.label = os.path.basename(path)
        self.manifest = None
        self.scalars = []        # (step, t, loss) train records, step order
        self.evals = []          # eval-event records
        self.dynamics = None     # last per-layer dynamics record
        self.layer_incidents = []
        for r in records:
            typ = r.get('type')
            if typ == 'manifest':
                # a process emits one manifest PER fit (run_seq-tagged);
                # the latest one describes the run this log's final
                # state belongs to
                self.manifest = r
            elif typ == 'scalars':
                if r.get('event') == 'eval':
                    self.evals.append(r)
                elif r.get('step') is not None:
                    self.scalars.append((int(r['step']), r.get('t'),
                                         r.get('loss')))
            elif typ == 'dynamics':
                if r.get('event') == 'layer_nonfinite':
                    self.layer_incidents.append(r)
                elif r.get('layers'):
                    self.dynamics = r
            elif typ == 'summary' and self.manifest is None:
                man = (r.get('ledger') or {}).get('manifest')
                if man:
                    self.manifest = man
        self.scalars.sort(key=lambda p: p[0])

    # -- derived ------------------------------------------------------------
    @property
    def steps(self):
        return self.scalars[-1][0] if self.scalars else None

    def loss_at(self, step):
        """The loss at the last banked point <= step (None without
        one)."""
        best = None
        for s, _, loss in self.scalars:
            if s > step:
                break
            if loss is not None:
                best = loss
        return best

    def final_loss(self):
        for _, _, loss in reversed(self.scalars):
            if loss is not None:
                return loss
        return None

    def nonfinite(self):
        """True when any banked loss is non-finite or a named-layer
        dynamics incident was recorded."""
        if self.layer_incidents:
            return True
        return any(loss is not None and not math.isfinite(loss)
                   for _, _, loss in self.scalars)

    def final_evals(self):
        """{metric_name: value} from each metric's LAST banked
        eval-event record (epoch-end train/val metrics)."""
        out = {}
        for r in self.evals:
            for k, v in r.items():
                if k.startswith('eval_') and isinstance(v, (int, float)):
                    out[k[len('eval_'):]] = v
        return out

    def time_to_loss(self, target):
        if target is None or not self.scalars:
            return None
        t0 = self.scalars[0][1]
        if t0 is None:
            return None
        for _, t, loss in self.scalars:
            if _finite(loss) and loss <= target and t is not None:
                return t - t0
        return None

    def step_time_ms(self):
        """Median wall-ms per step between consecutive scalar
        records."""
        deltas = []
        for (s0, t0, _), (s1, t1, _) in zip(self.scalars,
                                            self.scalars[1:]):
            if t0 is not None and t1 is not None and s1 > s0 \
                    and t1 > t0:
                deltas.append((t1 - t0) / (s1 - s0) * 1e3)
        return statistics.median(deltas) if deltas else None


def load_run(path):
    import telemetry_report
    return Run(path, telemetry_report.load(path))


# ---------------------------------------------------------------------------
# manifest + per-layer diffs
# ---------------------------------------------------------------------------

# per-run output locations: any two comparable runs necessarily differ
# here (two runs can't share one JSONL) — never a config signal, and
# the noise would bury the real flag diff the feature exists to surface
_PER_RUN_FLAGS = frozenset({'MXTPU_TELEMETRY_PATH', 'MXTPU_TFEVENTS_DIR',
                            'MXTPU_XPROF_DIR', 'MXTPU_CKPT_DIR'})


def manifest_diff(base, cand):
    """Lines describing how the candidate's manifest differs — flags
    first (the usual explanation), then environment."""
    from mxnet_tpu.telemetry.ledger import MANIFEST_KEYS
    lines = []
    mb, mc = base.manifest or {}, cand.manifest or {}
    fb, fc = mb.get('flags') or {}, mc.get('flags') or {}
    changed = sorted(k for k in set(fb) | set(fc)
                     if k not in _PER_RUN_FLAGS
                     and fb.get(k) != fc.get(k))
    if changed:
        lines.append('  flags: %s' % '; '.join(
            '%s %r -> %r' % (k, fb.get(k), fc.get(k)) for k in changed))
    for key in MANIFEST_KEYS:
        if mb.get(key) != mc.get(key):
            lines.append('  %s: %r -> %r' % (key, mb.get(key),
                                             mc.get(key)))
    return lines


def layer_drift(base, cand, tol_pct):
    """[(layer, stat, base, cand, delta_pct)] for common layers whose
    grad_norm / update_ratio moved past tol_pct, worst first."""
    if base.dynamics is None or cand.dynamics is None:
        return None
    lb, lc = base.dynamics['layers'], cand.dynamics['layers']
    out = []
    for layer in sorted(set(lb) & set(lc)):
        for stat in ('update_ratio', 'grad_norm'):
            vb, vc = lb[layer].get(stat), lc[layer].get(stat)
            if vc is None and vb is not None:
                out.append((layer, stat, vb, vc, float('inf')))
                continue
            if not _finite(vb) or not _finite(vc) or vb == 0:
                continue
            delta = (vc - vb) / vb * 100.0
            if abs(delta) > tol_pct:
                out.append((layer, stat, vb, vc, delta))
    out.sort(key=lambda r: -abs(r[4]))
    return out


# ---------------------------------------------------------------------------
# the diff
# ---------------------------------------------------------------------------

def extract(run, last_common, target):
    out = {}
    v = run.loss_at(last_common) if last_common is not None else None
    if v is not None:
        out['loss_at_step'] = v
    v = run.final_loss()
    if v is not None:
        out['final_loss'] = v
    v = run.time_to_loss(target)
    if v is not None:
        out['time_to_loss'] = v
    v = run.step_time_ms()
    if v is not None:
        out['step_time_ms'] = v
    return out


def diff(base, cand, tols, target):
    last_common = None
    if base.steps is not None and cand.steps is not None:
        last_common = min(base.steps, cand.steps)
    mb = extract(base, last_common, target)
    mc = extract(cand, last_common, target)
    rows = []
    for metric in _ORDER:
        vb, vc = mb.get(metric), mc.get(metric)
        if vb is None or vc is None:
            if metric == 'time_to_loss' and vb is not None \
                    and vc is None and cand.steps is not None \
                    and base.steps is not None \
                    and cand.steps >= base.steps:
                # the candidate trained at least as long and never
                # reached the target the baseline reached
                rows.append((metric, vb, vc, None, tols[metric],
                             'REGRESSION (target never reached)'))
            elif vc is not None:
                rows.append((metric, vb, vc, None, tols[metric],
                             'skipped (no baseline)'))
            elif vb is not None:
                rows.append((metric, vb, vc, None, tols[metric],
                             'skipped (missing in candidate)'))
            continue
        if not math.isfinite(vb):
            # a non-finite baseline can't certify anything — render a
            # visible skip (both-sides-NaN lands here too: a diverged
            # baseline is not comparative evidence, same rule as the
            # DIVERGED verdict below)
            rows.append((metric, vb, vc, None, tols[metric],
                         'skipped (baseline non-finite)'))
            continue
        if not math.isfinite(vc):
            rows.append((metric, vb, vc, None, tols[metric],
                         'REGRESSION (non-finite)'))
            continue
        delta = (vc - vb) / vb * 100.0 if vb else \
            (float('inf') if vc > 0 else 0.0)
        bad = delta > tols[metric]
        rows.append((metric, vb, vc, delta, tols[metric],
                     'REGRESSION' if bad else 'ok'))
    return rows, last_common


def _fmt_v(v):
    if v is None:
        return '-'
    if abs(v) >= 1e6:
        return '%.3e' % v
    return ('%.4f' % v).rstrip('0').rstrip('.')


def render(rows, base, cand, last_common):
    head = 'run compare: %s -> %s' % (base.label, cand.label)
    if last_common is not None:
        head += ' (last common step %d)' % last_common
    lines = [head,
             '  %-16s %14s %14s %9s %7s  %s'
             % ('metric', 'baseline', 'candidate', 'delta%', 'tol%',
                'verdict')]
    for metric, vb, vc, delta, tol, verdict in rows:
        lines.append('  %-16s %14s %14s %9s %7s  %s'
                     % (metric, _fmt_v(vb), _fmt_v(vc),
                        '-' if delta is None else '%+.1f' % delta,
                        '%.1f' % tol, verdict))
    return '\n'.join(lines)


def compare_pair(base, cand, tols, target, layer_tol):
    """Print one baseline->candidate comparison; returns True when the
    candidate regressed/diverged."""
    man = manifest_diff(base, cand)
    if man:
        print('config diff (%s -> %s):' % (base.label, cand.label))
        for line in man:
            print(line)
    rows, last_common = diff(base, cand, tols, target)
    print(render(rows, base, cand, last_common))
    skipped = [r for r in rows if r[5].startswith('skipped')]
    if skipped:
        print('note: ungated — %s'
              % '; '.join('%s %s' % (r[0], r[5][len('skipped '):])
                          for r in skipped))
    ev_b, ev_c = base.final_evals(), cand.final_evals()
    common = sorted(set(ev_b) & set(ev_c))
    if common:
        # informational (no verdict: metric direction isn't knowable
        # in general — accuracy rises, cross-entropy falls)
        print('eval metrics (last banked):')
        for name in common:
            vb, vc = ev_b[name], ev_c[name]
            print('  %-24s %12s -> %-12s %s'
                  % (name, _fmt_v(vb), _fmt_v(vc),
                     '%+.1f%%' % ((vc - vb) / vb * 100.0) if vb else '-'))
    bad = [r for r in rows if r[5].startswith('REGRESSION')]
    if base.nonfinite():
        print('warning: baseline %s itself went non-finite — its loss '
              'gates are skipped and cannot certify the candidate'
              % base.label)
    diverged = cand.nonfinite() and not base.nonfinite()
    if diverged:
        why = ''
        if cand.layer_incidents:
            first = cand.layer_incidents[0]
            why = ' — layer %s %s non-finite%s' % (
                first.get('layer', '?'), first.get('stat', '?'),
                ' at step %s' % first['step']
                if first.get('step') is not None else '')
        print('DIVERGED: %s went non-finite%s' % (cand.label, why))
    drift = layer_drift(base, cand, layer_tol)
    if drift is None:
        print('note: per-layer dynamics not banked on both sides '
              '(MXTPU_DYNAMICS=1 records them) — layer attribution '
              'unavailable')
    elif drift:
        print('layer drift (> %.0f%%):' % layer_tol)
        for layer, stat, vb, vc, delta in drift[:8]:
            print('  %-24s %-13s %12s -> %-12s %s'
                  % (layer, stat, _fmt_v(vb), _fmt_v(vc),
                     'non-finite' if not math.isfinite(delta)
                     else '%+.1f%%' % delta))
        if bad or diverged:
            worst = drift[0]
            print('worst layer: %s (%s %s)' % (
                worst[0], worst[1],
                'non-finite' if not math.isfinite(worst[4])
                else '%+.1f%%' % worst[4]))
    if bad:
        print('REGRESSION: %s' % ', '.join(r[0] for r in bad))
    return bool(bad) or diverged


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='Diff two or more runs by their telemetry ledgers '
                    '(manifest, scalars timeseries, per-layer dynamics) '
                    'with per-metric tolerance; non-zero exit on a '
                    'regressed or diverged candidate — the run-level '
                    'sibling of tools/bench_diff.py '
                    '(docs/observability.md, "Comparing runs").')
    ap.add_argument('baseline', help='baseline telemetry JSONL')
    ap.add_argument('candidates', nargs='+',
                    help='candidate telemetry JSONL(s), each compared '
                         'against the baseline')
    ap.add_argument('--tol-pct', type=float, default=None,
                    help='one tolerance (%%) for every metric (default: '
                         'per-metric — loss 5%%, time-to-loss 20%%, '
                         'step time 10%%)')
    ap.add_argument('--tol', action='append', default=[],
                    metavar='METRIC=PCT',
                    help='per-metric tolerance override, e.g. '
                         '--tol final_loss=2 (repeatable)')
    ap.add_argument('--target-loss', type=float, default=None,
                    help='time-to-loss target (default: the baseline '
                         'run\'s final loss)')
    ap.add_argument('--layer-tol-pct', type=float, default=50.0,
                    help='per-layer dynamics drift threshold (%%) for '
                         'the layer-attribution table (default 50)')
    args = ap.parse_args(argv)
    tols = dict(_DEF_TOL)
    if args.tol_pct is not None:
        tols = {k: args.tol_pct for k in tols}
    for spec in args.tol:
        name, _, pct = spec.partition('=')
        if name not in tols or not pct:
            ap.error('unknown --tol %r (metrics: %s)'
                     % (spec, ', '.join(sorted(tols))))
        tols[name] = float(pct)
    base = load_run(args.baseline)
    if not base.scalars:
        print('run_compare: %s banked no scalars records (set '
              'MXTPU_TELEMETRY=1 and MXTPU_SCALARS_EVERY>0)'
              % args.baseline)
        return 2
    rc = 0
    for i, path in enumerate(args.candidates):
        if i:
            print()
        cand = load_run(path)
        if not cand.scalars:
            print('run_compare: %s banked no scalars records' % path)
            rc = max(rc, 2)
            continue
        target = args.target_loss
        if target is None:
            target = base.final_loss()
        if compare_pair(base, cand, tols, target, args.layer_tol_pct):
            rc = max(rc, 1)
    return rc


if __name__ == '__main__':
    sys.exit(main())
