#!/bin/bash
# Tunnel recovery watcher: probe the chip in a throwaway subprocess every
# ~8 min; on the first healthy probe, run tools/tpu_capture.sh once and
# exit. Writes progress to docs/tpu_artifacts/watch.log.
cd "$(dirname "$0")/.."
OUT=docs/tpu_artifacts
mkdir -p "$OUT"
LOG="$OUT/watch.log"
for i in $(seq 1 "${1:-60}"); do
  echo "$(date -u +%H:%M:%S) probe $i" >> "$LOG"
  if timeout 240 python -c 'import jax; assert any(d.platform=="tpu" for d in jax.devices())' 2>>"$LOG"; then
    echo "$(date -u +%H:%M:%S) chip healthy; capturing" >> "$LOG"
    bash tools/tpu_capture.sh >> "$LOG" 2>&1
    echo "$(date -u +%H:%M:%S) capture done" >> "$LOG"
    exit 0
  fi
  sleep 480
done
echo "$(date -u +%H:%M:%S) gave up" >> "$LOG"
exit 1
