#!/usr/bin/env python
"""Render the roofline attribution block from a telemetry JSONL log,
offline.

A run with ``MXTPU_TELEMETRY=1 MXTPU_ROOFLINE=1`` appends a
``roofline`` record (and folds the same dict into the ``summary``
record) carrying the per-layer achieved-vs-peak analysis. This tool
re-renders it without re-running anything::

    python tools/roofline_report.py telemetry.jsonl

Uses the SAME renderer as the live end-of-run summary
(mxnet_tpu/telemetry/export.py::_roofline_lines), so the offline block
is byte-identical to the one the run logged — the round-trip the
roofline tests pin. ``--json`` dumps the raw analysis dict instead
(for scripting: jq over layers/classes/headroom). Multiple records
(several write_summary calls, or several bench rounds appending to one
log) keep the LAST one — the end-of-run view — unless ``--all`` lists
every one with its timestamp.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from mxnet_tpu.telemetry.export import _roofline_lines  # noqa: E402
from telemetry_report import load  # noqa: E402  (same loader conventions)


def roofline_records(records):
    """Every roofline analysis dict in a parsed record list, oldest
    first: the dedicated ``roofline`` records, plus any ``summary``
    record's ``roofline`` key (a crashed run may have either)."""
    out = []
    for r in records:
        if r.get('type') == 'roofline':
            out.append((r.get('t'), {k: v for k, v in r.items()
                                     if k not in ('type', 't', 'host')}))
        elif r.get('type') == 'summary' and r.get('roofline'):
            out.append((r.get('t'), r['roofline']))
    return out


def render(roof):
    """One analysis dict -> the summary-table block, as a string."""
    return '\n'.join(_roofline_lines(roof))


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='Render the roofline attribution block (per-layer '
                    'compute-/memory-/overhead-bound classification, '
                    'achieved/peak %, headroom, collective accounting) '
                    'from a telemetry JSONL log, offline — byte-identical '
                    'to the block the live summary table logged.')
    ap.add_argument('path', help='telemetry JSONL file to render')
    ap.add_argument('--json', action='store_true',
                    help='dump the raw analysis dict(s) as JSON instead '
                         'of the rendered block')
    ap.add_argument('--all', action='store_true',
                    help='render every roofline record in the log, not '
                         'just the last')
    args = ap.parse_args(argv)
    recs = roofline_records(load(args.path))
    if not recs:
        sys.stderr.write(
            'roofline_report: %s holds no roofline record — was the run '
            'started with MXTPU_TELEMETRY=1 MXTPU_ROOFLINE=1?\n'
            % args.path)
        return 1
    picked = recs if args.all else recs[-1:]
    if args.json:
        dicts = [r for _t, r in picked]
        print(json.dumps(dicts[0] if len(dicts) == 1 else dicts,
                         indent=2))
        return 0
    blocks = []
    for t, roof in picked:
        if args.all and t is not None:
            blocks.append('== t=%s ==' % t)
        blocks.append(render(roof))
    print('\n'.join(blocks))
    return 0


if __name__ == '__main__':
    sys.exit(main())
