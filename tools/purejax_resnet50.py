"""Pure-JAX ResNet-50 control + per-op time breakdown (VERDICT r3 #1).

Two modes, both chip-safe under the round-3 capture discipline (probe
in a throwaway subprocess first, sync via host fetch, never attach the
profiler through the tunnel):

  python tools/purejax_resnet50.py            # control train-step bench
  python tools/purejax_resnet50.py breakdown  # per-conv-op microbench

**control** builds a ResNet-50 v1 train step in *raw JAX only* — no
mxnet_tpu imports anywhere near the compute path — with the exact
bench.py configuration (batch 32 synthetic data, bf16 compute, fp32
masters, SGD momentum+wd, BN running-stat updates, lax.scan
steps-per-call fusion, donated buffers). If its img/s matches
bench.py's, the framework adds no overhead and the remaining MFU gap
is XLA's conv lowering on this chip; if it is materially faster, the
delta is framework overhead to hunt down.

**breakdown** enumerates every (conv config x {fwd, bwd_input,
bwd_filter}) in ResNet-50 batch-32 and times each *individually* on
the device (data-dependent scan chain so XLA cannot overlap
iterations), emitting per-op ms, FLOPs, and MFU. This substitutes for
a per-HLO profile: the profiler cannot attach through the axon tunnel
(a killed trace wedges the chip claim — see .claude/skills/verify),
so the breakdown is measured op-by-op instead of sampled.

Output: one JSON line per result on stdout; artifacts are banked by
tools/tpu_capture.sh into docs/tpu_artifacts/.
"""
import json
import os
import sys
import time

import numpy as np

BATCH = int(os.environ.get('MXTPU_BENCH_BATCH', '32'))
STEPS_PER_CALL = int(os.environ.get('MXTPU_BENCH_STEPS_PER_CALL', '32'))
PEAK_BF16 = {'v6': 918e12, 'v5p': 459e12, 'v5': 197e12,
             'v4': 275e12, 'v3': 123e12, 'v2': 45e12}


def _log(msg):
    print('[purejax] ' + msg, file=sys.stderr, flush=True)


def _probe():
    import subprocess
    code = 'import jax; print("PROBE_OK", jax.devices()[0].platform)'
    try:
        out = subprocess.run([sys.executable, '-c', code], timeout=240,
                             capture_output=True, text=True).stdout
    except Exception as e:  # noqa: BLE001
        _log('probe failed: %s' % e)
        return False
    return 'PROBE_OK' in (out or '')


def _peak(device):
    kind = (getattr(device, 'device_kind', '') or '').lower()
    for sub, p in PEAK_BF16.items():
        if sub in kind:
            return p, kind
    return 0.0, kind


# ---------------------------------------------------------------------------
# ResNet-50 v1 in raw JAX (NHWC compute, bf16, BN running stats)
# ---------------------------------------------------------------------------

STAGES = [(3, 64, 256, 1), (4, 128, 512, 2), (6, 256, 1024, 2),
          (3, 512, 2048, 2)]


def init_params(rng):
    """params: list of (kind, array) fp32; kinds: conv HWIO, bn (gamma,
    beta), fc (w, b). Returns (params, bn_stats)."""
    params, stats = [], []

    def conv(kh, kw, cin, cout):
        std = (2.0 / (kh * kw * cin)) ** 0.5
        params.append(('conv', (rng.standard_normal(
            (kh, kw, cin, cout)) * std).astype(np.float32)))

    def bn(c):
        params.append(('gamma', np.ones((c,), np.float32)))
        params.append(('beta', np.zeros((c,), np.float32)))
        stats.append(np.zeros((c,), np.float32))   # mean
        stats.append(np.ones((c,), np.float32))    # var

    conv(7, 7, 3, 64)
    bn(64)
    cin = 64
    for n_blocks, mid, cout, stride in STAGES:
        for b in range(n_blocks):
            s = stride if b == 0 else 1
            if b == 0:
                conv(1, 1, cin, cout)   # projection shortcut
                bn(cout)
            conv(1, 1, cin, mid)
            bn(mid)
            conv(3, 3, mid, mid)        # stride s
            bn(mid)
            conv(1, 1, mid, cout)
            bn(cout)
            cin = cout
    std = (2.0 / 2048) ** 0.5
    params.append(('fc_w', (rng.standard_normal(
        (2048, 1000)) * std).astype(np.float32)))
    params.append(('fc_b', np.zeros((1000,), np.float32)))
    return params, stats


def forward(param_arrays, kinds, stats, x, train=True, momentum=0.9):
    """x: (N,H,W,C) bf16. Returns (logits fp32, new_stats)."""
    import jax
    import jax.numpy as jnp

    it = iter(param_arrays)
    sit = iter(stats)
    new_stats = []

    def conv(x, w, stride):
        return jax.lax.conv_general_dilated(
            x, w.astype(x.dtype), (stride, stride),
            [((w.shape[0] - 1) // 2, w.shape[0] // 2)] * 2,
            dimension_numbers=('NHWC', 'HWIO', 'NHWC'))

    def bnorm(x):
        gamma, beta = next(it), next(it)
        rmean, rvar = next(sit), next(sit)
        if train:
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, (0, 1, 2))
            var = jnp.var(xf, (0, 1, 2))
            new_stats.append(momentum * rmean + (1 - momentum) * mean)
            new_stats.append(momentum * rvar + (1 - momentum) * var)
        else:
            mean, var = rmean, rvar
            new_stats.extend([rmean, rvar])
        inv = jax.lax.rsqrt(var + 1e-5) * gamma
        return ((x.astype(jnp.float32) - mean) * inv + beta).astype(x.dtype)

    x = conv(x, next(it), 2)
    x = jax.nn.relu(bnorm(x))
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
        [(0, 0), (1, 1), (1, 1), (0, 0)])
    for n_blocks, mid, cout, stride in STAGES:
        for b in range(n_blocks):
            s = stride if b == 0 else 1
            if b == 0:
                sc = conv(x, next(it), s)
                sc = bnorm(sc)
            else:
                sc = x
            # v1 semantics (matches the framework's BottleneckV1,
            # gluon/model_zoo/vision/resnet.py: stride on the FIRST
            # 1x1 conv, not the 3x3 — v1.5 would be ~12% more FLOPs)
            h = jax.nn.relu(bnorm(conv(x, next(it), s)))
            h = jax.nn.relu(bnorm(conv(h, next(it), 1)))
            h = bnorm(conv(h, next(it), 1))
            x = jax.nn.relu(h + sc)
    x = jnp.mean(x.astype(jnp.float32), (1, 2))
    return x @ next(it) + next(it), new_stats


def control_bench():
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    params, stats = init_params(rng)
    kinds = [k for k, _ in params]
    masters = tuple(jnp.asarray(a) for _, a in params)
    stats = tuple(jnp.asarray(s) for s in stats)
    vel = tuple(jnp.zeros_like(m) for m in masters)
    images = jnp.asarray(rng.standard_normal((BATCH, 224, 224, 3)),
                         jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, 1000, (BATCH,)), jnp.int32)
    lr, mom, wd = 0.1, 0.9, 1e-4

    def one_step(carry, _):
        masters, stats, vel = carry

        def loss_fn(bf16):
            logits, new_stats = forward(bf16, kinds, stats, images)
            logits = logits.astype(jnp.float32)
            lse = jax.nn.logsumexp(logits, -1)
            gold = jnp.take_along_axis(logits, labels[:, None], -1)[:, 0]
            return jnp.mean(lse - gold), new_stats

        bf16 = tuple(m.astype(jnp.bfloat16) for m in masters)
        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(bf16)
        new_m, new_v = [], []
        for m, g, v in zip(masters, grads, vel):
            g32 = g.astype(jnp.float32) + wd * m
            nv = mom * v + g32
            new_m.append(m - lr * nv)
            new_v.append(nv)
        return (tuple(new_m), tuple(new_stats), tuple(new_v)), loss

    def step(masters, stats, vel):
        (m, s, v), losses = jax.lax.scan(
            one_step, (masters, stats, vel), None, length=STEPS_PER_CALL)
        return m, s, v, losses[-1]

    t = time.perf_counter()
    jstep = jax.jit(step, donate_argnums=(0, 1, 2))
    compiled = jstep.lower(masters, stats, vel).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    flops_per_step = float(cost.get('flops', 0.0)) * STEPS_PER_CALL
    _log('compile %.1fs, flops/dispatch=%.3e'
         % (time.perf_counter() - t, flops_per_step))

    t = time.perf_counter()
    for _ in range(3):
        masters, stats, vel, loss = compiled(masters, stats, vel)
    loss_v = float(np.asarray(loss))   # host fetch = true barrier
    warm = time.perf_counter() - t
    _log('warmup 3 calls %.1fs loss=%.3f' % (warm, loss_v))

    calls = int(min(60, max(8, 15.0 / max(1e-3, warm / 3))))
    t0 = time.perf_counter()
    for _ in range(calls):
        masters, stats, vel, loss = compiled(masters, stats, vel)
    float(np.asarray(loss))
    dt = time.perf_counter() - t0

    dev = jax.devices()[0]
    peak, kind = _peak(dev)
    img_s = calls * STEPS_PER_CALL * BATCH / dt
    mfu = flops_per_step * calls / dt / peak if peak else None
    out = {'metric': 'purejax_resnet50_control', 'value': round(img_s, 2),
           'unit': 'images/sec', 'batch': BATCH,
           'steps_per_call': STEPS_PER_CALL, 'device': kind,
           'platform': dev.platform}
    if mfu is not None:
        out['mfu'] = round(mfu, 4)
    print(json.dumps(out), flush=True)


# ---------------------------------------------------------------------------
# Per-op breakdown
# ---------------------------------------------------------------------------

def conv_configs():
    """Every conv in ResNet-50 batch-BATCH as (count, H, W, cin, cout,
    k, stride) — H,W are the *input* spatial dims."""
    cfgs = {}

    def add(h, cin, cout, k, s):
        key = (h, cin, cout, k, s)
        cfgs[key] = cfgs.get(key, 0) + 1

    add(224, 3, 64, 7, 2)
    h, cin = 56, 64
    for n_blocks, mid, cout, stride in STAGES:
        for b in range(n_blocks):
            s = stride if b == 0 else 1
            if b == 0:
                add(h, cin, cout, 1, s)
            # v1: stride rides the first 1x1 (see forward())
            add(h, cin, mid, 1, s)
            add(h // s, mid, mid, 3, 1)
            add(h // s, mid, cout, 1, 1)
            cin = cout
            if b == 0:
                h //= s
    return [(c,) + k for k, c in cfgs.items()]


def breakdown():
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    peak, kind = _peak(dev)
    rng = np.random.RandomState(0)
    rows = []
    R1, R2 = 32, 160

    def timed(fn, *args):
        """Per-rep time via a two-point fit: run a data-dependent scan
        chain at lengths R1 and R2 and take the slope
        (T2 - T1) / (R2 - R1). The tunneled runtime adds a large,
        roughly constant per-dispatch+fetch cost (~65 ms measured);
        differencing cancels it exactly where dividing by REPS leaves
        it as a floor. Returns ONLY a scalar to the host (a full-output
        fetch through the tunnel would dwarf the op), and chains
        iterations with a 1e-30-scaled tap — numerically identity in
        bf16 but not symbolically zero, so XLA cannot fold the
        dependency away and hoist the op out of the loop."""
        def chain_of(reps):
            def chain(args):
                def body(c, _):
                    out = fn(*c)
                    # sum over the WHOLE output: a sliced tap lets
                    # XLA slice the conv itself down to one column
                    # (observed as >100% MFU); the full reduction is
                    # fused into the conv epilogue
                    tap = jnp.sum(out.astype(jnp.float32)) * 1e-30
                    return tuple(a * (1 + tap).astype(a.dtype)
                                 if i == 0 else a
                                 for i, a in enumerate(c)), ()
                c, _ = jax.lax.scan(body, args, None, length=reps)
                return jnp.sum(fn(*c).astype(jnp.float32))
            comp = jax.jit(chain).lower(args).compile()
            float(np.asarray(comp(args)))   # warmup + barrier
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                float(np.asarray(comp(args)))
                ts.append(time.perf_counter() - t0)
            return sorted(ts)[1]
        return max(1e-9, (chain_of(R2) - chain_of(R1)) / (R2 - R1))

    for count, h, cin, cout, k, s in conv_configs():
        x = jnp.asarray(rng.standard_normal((BATCH, h, h, cin)),
                        jnp.bfloat16)
        w = jnp.asarray(rng.standard_normal((k, k, cin, cout)) * 0.05,
                        jnp.bfloat16)
        pad = [((k - 1) // 2, k // 2)] * 2

        def conv(x, w, stride=s, pad=pad):
            return jax.lax.conv_general_dilated(
                x, w, (stride, stride), pad,
                dimension_numbers=('NHWC', 'HWIO', 'NHWC'))

        ho = h // s
        flops = 2.0 * BATCH * ho * ho * cin * cout * k * k
        y = jnp.asarray(rng.standard_normal((BATCH, ho, ho, cout)),
                        jnp.bfloat16)

        def bwd_in(y, w, x=x):
            _, vjp = jax.vjp(lambda xx: conv(xx, w), x)
            return vjp(y)[0]

        def bwd_w(y, x, w=w):
            _, vjp = jax.vjp(lambda ww: conv(x, ww), w)
            return vjp(y)[0]

        for mode, fn, args in (('fwd', conv, (x, w)),
                               ('bwd_input', bwd_in, (y, w)),
                               ('bwd_filter', bwd_w, (y, x))):
            dt = timed(fn, *args)
            mfu = flops / dt / peak if peak else None
            rows.append({'op': 'conv', 'mode': mode, 'count': count,
                         'in_hw': h, 'cin': cin, 'cout': cout, 'k': k,
                         'stride': s, 'ms': round(dt * 1e3, 4),
                         'gflops': round(flops / 1e9, 2),
                         'mfu': round(mfu, 4) if mfu is not None else None,
                         'total_ms': round(dt * 1e3 * count, 4)})
            _log('%s k=%d s=%d %dx%d %d->%d x%d: %.3f ms  mfu=%.1f%%'
                 % (mode, k, s, h, h, cin, cout, count, dt * 1e3,
                    100 * (mfu or 0)))

    # FC layer fwd+bwd for completeness
    x = jnp.asarray(rng.standard_normal((BATCH, 2048)), jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((2048, 1000)) * 0.02, jnp.bfloat16)
    dt = timed(lambda x, w: x @ w, x, w)
    rows.append({'op': 'fc', 'mode': 'fwd', 'count': 1, 'ms':
                 round(dt * 1e3, 4),
                 'gflops': round(2.0 * BATCH * 2048 * 1000 / 1e9, 3)})

    conv_rows = [r for r in rows if r['op'] == 'conv']
    total = {m: sum(r['total_ms'] for r in conv_rows if r['mode'] == m)
             for m in ('fwd', 'bwd_input', 'bwd_filter')}
    summary = {'metric': 'resnet50_conv_op_breakdown', 'batch': BATCH,
               'device': kind, 'sum_ms_per_step': {
                   k: round(v, 3) for k, v in total.items()},
               'worst_bwd_filter': sorted(
                   (r for r in conv_rows if r['mode'] == 'bwd_filter'),
                   key=lambda r: -r['total_ms'])[:5],
               'rows': rows}
    print(json.dumps(summary), flush=True)


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else 'control'
    _log('probing backend in throwaway subprocess...')
    if not _probe():
        _log('chip unreachable; refusing to init in-process')
        sys.exit(2)
    import jax
    _log('backend: %s' % jax.devices())
    if mode == 'control':
        control_bench()
    else:
        breakdown()


if __name__ == '__main__':
    main()
