#!/usr/bin/env python
"""Launch a distributed KVStore job: scheduler + servers + workers.

Reference: tools/launch.py:29-47 (delegates to the dmlc-core tracker for
ssh/mpi/yarn/local). This implements the `local` launcher — every role runs
as a local subprocess with the DMLC_* env protocol
(include/mxnet/kvstore.h:244-301):

    python tools/launch.py -n 4 -s 2 python my_training_script.py

Server and scheduler processes just `import mxnet_tpu`; the role loop in
kvstore_server.init_server_module_if_needed takes over (reference
python/mxnet/kvstore_server.py:75).

Worker stdout/stderr is prefixed ``[h<i>]`` so interleaved multi-process
output attributes to a host, and the launcher's exit code is the FIRST
worker failure in completion order (the root cause — later workers die
of follow-on collective errors with less informative codes).

This launcher runs ONE attempt; it does not supervise. For gang
semantics — tear down the survivors when one worker dies unclean,
relaunch the whole job on a fresh coordinator port against a restart
budget, optionally shrink the worker set after a host loss — wrap the
job in ``tools/gang_supervisor.py`` instead.
"""
import argparse
import os
import socket
import subprocess
import sys
import threading
import time


def _reserve_port():
    """(socket, port): an OS-assigned port with the reserving socket
    still OPEN — the caller closes it immediately before spawning the
    process that binds it. The old close-at-pick free_port() left the
    port up for grabs for the WHOLE setup stretch (spawning a scheduler
    + N servers); this shrinks the race to the close->bind window, and
    init_multihost's bounded join retry covers that residue."""
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(('127.0.0.1', 0))
    return s, s.getsockname()[1]


def _pump(stream, sink, prefix):
    """Forward one worker pipe line-by-line with the ``[h<i>]`` host
    prefix (daemon thread; binary-safe, flushed per line so interleaved
    gang output stays attributable)."""
    try:
        for line in iter(stream.readline, b''):
            sink.write(prefix + line)
            sink.flush()
    except ValueError:          # sink closed at interpreter teardown
        pass
    finally:
        stream.close()


def start_worker(cmd, env, idx, out=None, err=None):
    """Spawn one worker with ``[h<idx>]``-prefixed stdout/stderr pumps.
    ``out``/``err`` default to this process's binary stdio (the gang
    supervisor passes its own sinks)."""
    prefix = ('[h%d] ' % idx).encode()
    env = dict(env)
    # the pipes below replace the tty the worker used to inherit: a
    # Python worker would block-buffer ~8KB, delaying live output and
    # LOSING the buffered tail — the diagnostic the prefixing exists
    # for — when a wedged worker is SIGKILLed. Harmless for non-Python
    # commands; an operator's explicit setting wins
    env.setdefault('PYTHONUNBUFFERED', '1')
    p = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE)
    p._mxtpu_pumps = []
    for stream, sink in ((p.stdout, out or sys.stdout.buffer),
                        (p.stderr, err or sys.stderr.buffer)):
        t = threading.Thread(target=_pump, args=(stream, sink, prefix),
                             daemon=True)
        t.start()
        p._mxtpu_pumps.append(t)
    return p


def join_pumps(workers, timeout=5.0):
    """Drain the output pumps of EXITED workers before the launcher
    process returns: the pumps are daemon threads, and interpreter
    shutdown would otherwise drop the buffered tail of a failing
    worker's pipe — exactly the root-cause traceback the [h<i>]
    prefixing exists to preserve. Bounded: the workers are dead, so
    EOF is a read away."""
    deadline = time.time() + timeout
    for p in workers:
        for t in getattr(p, '_mxtpu_pumps', ()):
            t.join(timeout=max(0.1, deadline - time.time()))


def wait_first_failure(workers, poll_s=0.05):
    """Wait for every worker; return the exit code of the FIRST one to
    fail in COMPLETION order (the root cause of a gang death — the old
    list-order scan reported whichever low-index worker died last of a
    follow-on collective error), or 0 when all exit clean."""
    rc = 0
    pending = dict(enumerate(workers))
    while pending:
        for i, p in sorted(pending.items()):
            code = p.poll()
            if code is None:
                continue
            del pending[i]
            if code != 0 and rc == 0:
                rc = code
        if pending:
            time.sleep(poll_s)
    return rc


def main():
    ap = argparse.ArgumentParser(description='Launch a distributed job')
    ap.add_argument('-n', '--num-workers', type=int, required=True)
    ap.add_argument('-s', '--num-servers', type=int, default=None,
                    help='default: same as --num-workers')
    ap.add_argument('--launcher', choices=['local'], default='local')
    ap.add_argument('--sync-dst-dir', default=None,
                    help='accepted for reference CLI compat; unused locally')
    ap.add_argument('command', nargs=argparse.REMAINDER)
    args = ap.parse_args()
    # REMAINDER keeps a leading '--' separator; drop it (reference
    # launch.py accepts both `launch.py -n 2 cmd` and `-n 2 -- cmd`)
    if args.command and args.command[0] == '--':
        args.command = args.command[1:]
    if not args.command:
        ap.error('no command given')
    num_servers = (args.num_servers if args.num_servers is not None
                   else args.num_workers)

    # reserve both rendezvous ports with OPEN sockets until their
    # binding process is about to spawn (see _reserve_port)
    root_sock, root_port = _reserve_port()
    coord_sock, coord_port = _reserve_port()
    base_env = dict(os.environ)
    base_env.update({
        'DMLC_PS_ROOT_URI': '127.0.0.1',
        'DMLC_PS_ROOT_PORT': str(root_port),
        'DMLC_NUM_WORKER': str(args.num_workers),
        'DMLC_NUM_SERVER': str(num_servers),
        # jax.distributed bridge (parallel/multihost.py): workers can
        # join one SPMD job with XLA collectives instead of (or beside)
        # the PS tier
        'MXTPU_COORDINATOR': '127.0.0.1:%d' % coord_port,
        'MXTPU_NUM_HOSTS': str(args.num_workers),
    })
    # role processes must be able to import mxnet_tpu from any cwd
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_env['PYTHONPATH'] = (repo + os.pathsep + base_env['PYTHONPATH']
                              if base_env.get('PYTHONPATH') else repo)
    role_cmd = [sys.executable, '-c', 'import mxnet_tpu']

    procs, workers = [], []
    # no PS tier requested (e.g. pure jax.distributed jobs): skip the
    # scheduler too, or workers would leave it blocking 20 s at exit
    scheduler_count = 1 if num_servers > 0 else 0
    root_sock.close()           # the scheduler binds it next
    try:
        for role, count, cmd in [('scheduler', scheduler_count, role_cmd),
                                 ('server', num_servers, role_cmd)]:
            for i in range(count):
                env = dict(base_env)
                env['DMLC_ROLE'] = role
                procs.append(subprocess.Popen(cmd, env=env))
        coord_sock.close()      # worker 0 binds the coordinator next
        for i in range(args.num_workers):
            env = dict(base_env)
            env['DMLC_ROLE'] = 'worker'
            env['MXTPU_HOST_ID'] = str(i)
            p = start_worker(args.command, env, i)
            procs.append(p)
            workers.append(p)
        rc = wait_first_failure(workers)
        join_pumps(workers)
        for p in procs:
            if p not in workers:
                try:
                    p.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    p.terminate()
        return rc
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


if __name__ == '__main__':
    sys.exit(main())
