#!/usr/bin/env python
"""Launch a distributed KVStore job: scheduler + servers + workers.

Reference: tools/launch.py:29-47 (delegates to the dmlc-core tracker for
ssh/mpi/yarn/local). This implements the `local` launcher — every role runs
as a local subprocess with the DMLC_* env protocol
(include/mxnet/kvstore.h:244-301):

    python tools/launch.py -n 4 -s 2 python my_training_script.py

Server and scheduler processes just `import mxnet_tpu`; the role loop in
kvstore_server.init_server_module_if_needed takes over (reference
python/mxnet/kvstore_server.py:75).
"""
import argparse
import os
import socket
import subprocess
import sys


def free_port():
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main():
    ap = argparse.ArgumentParser(description='Launch a distributed job')
    ap.add_argument('-n', '--num-workers', type=int, required=True)
    ap.add_argument('-s', '--num-servers', type=int, default=None,
                    help='default: same as --num-workers')
    ap.add_argument('--launcher', choices=['local'], default='local')
    ap.add_argument('--sync-dst-dir', default=None,
                    help='accepted for reference CLI compat; unused locally')
    ap.add_argument('command', nargs=argparse.REMAINDER)
    args = ap.parse_args()
    # REMAINDER keeps a leading '--' separator; drop it (reference
    # launch.py accepts both `launch.py -n 2 cmd` and `-n 2 -- cmd`)
    if args.command and args.command[0] == '--':
        args.command = args.command[1:]
    if not args.command:
        ap.error('no command given')
    num_servers = (args.num_servers if args.num_servers is not None
                   else args.num_workers)

    base_env = dict(os.environ)
    base_env.update({
        'DMLC_PS_ROOT_URI': '127.0.0.1',
        'DMLC_PS_ROOT_PORT': str(free_port()),
        'DMLC_NUM_WORKER': str(args.num_workers),
        'DMLC_NUM_SERVER': str(num_servers),
        # jax.distributed bridge (parallel/multihost.py): workers can
        # join one SPMD job with XLA collectives instead of (or beside)
        # the PS tier
        'MXTPU_COORDINATOR': '127.0.0.1:%d' % free_port(),
        'MXTPU_NUM_HOSTS': str(args.num_workers),
    })
    # role processes must be able to import mxnet_tpu from any cwd
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_env['PYTHONPATH'] = (repo + os.pathsep + base_env['PYTHONPATH']
                              if base_env.get('PYTHONPATH') else repo)
    role_cmd = [sys.executable, '-c', 'import mxnet_tpu']

    procs, workers = [], []
    # no PS tier requested (e.g. pure jax.distributed jobs): skip the
    # scheduler too, or workers would leave it blocking 20 s at exit
    scheduler_count = 1 if num_servers > 0 else 0
    try:
        for role, count, cmd in [('scheduler', scheduler_count, role_cmd),
                                 ('server', num_servers, role_cmd),
                                 ('worker', args.num_workers, args.command)]:
            for i in range(count):
                env = dict(base_env)
                env['DMLC_ROLE'] = role
                if role == 'worker':
                    env['MXTPU_HOST_ID'] = str(i)
                p = subprocess.Popen(cmd, env=env)
                procs.append(p)
                if role == 'worker':
                    workers.append(p)
        rc = 0
        for p in workers:
            p.wait()
            rc = rc or p.returncode
        for p in procs:
            if p not in workers:
                try:
                    p.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    p.terminate()
        return rc
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


if __name__ == '__main__':
    sys.exit(main())
