"""Headline benchmark: ResNet-50 training throughput (images/sec).

Mirrors the reference's `train_imagenet.py` perf table config
(docs/how_to/perf.md:150-190, batch 32, synthetic data): one full
training step — forward, softmax CE, backward, SGD-momentum update,
BatchNorm stat updates — compiled to a single donated-buffer XLA
computation via the Gluon hybridize path (the graph is the traced
ResNet-50 symbol; parameters are host-initialized to keep the setup off
the device's eager path).

vs_baseline divides by the strongest single-GPU reference number:
P100 batch-32 ResNet-50 training at 181.53 img/s (BASELINE.md).

Prints one JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import sys
import time

import numpy as np

BASELINE_IMG_S = 181.53  # P100, batch 32, docs/how_to/perf.md:150-190
BATCH = 32
WARMUP_STEPS = 3
BENCH_STEPS = 20


def _log(msg):
    print(msg, file=sys.stderr, flush=True)


def _host_init(name, shape, rng):
    """Host-side (numpy) parameter init by name convention — values only
    need to be numerically sane for a throughput bench."""
    if 'gamma' in name or 'var' in name:
        return np.ones(shape, np.float32)
    if 'beta' in name or 'bias' in name or 'mean' in name:
        return np.zeros(shape, np.float32)
    fan_in = int(np.prod(shape[1:])) if len(shape) > 1 else shape[0]
    std = (2.0 / max(1, fan_in)) ** 0.5
    return (rng.standard_normal(shape) * std).astype(np.float32)


def build_train_step():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from mxnet_tpu.executor import _GraphProgram

    net = resnet50_v1()
    net.hybridize()
    _, sym = net._get_graph(
        type('P', (), {'shape': (BATCH, 3, 224, 224),
                       'context': None})())  # placeholder-shaped trace
    prog = _GraphProgram(sym)
    arg_shapes, _, aux_shapes = sym.infer_shape(
        data=(BATCH, 3, 224, 224))
    arg_names, aux_names = prog.arg_names, prog.aux_names

    rng = np.random.RandomState(0)
    data_idx = arg_names.index('data')
    arg_arrays = []
    for name, shape in zip(arg_names, arg_shapes):
        arg_arrays.append(jnp.asarray(_host_init(name, shape, rng)))
    aux_arrays = tuple(jnp.asarray(_host_init(n, s, rng))
                       for n, s in zip(aux_names, aux_shapes))
    runner = prog.make_runner()

    lr, momentum, wd = 0.1, 0.9, 1e-4

    def step(args, aux, vel, images, labels, key):
        def loss_fn(args):
            a = list(args)
            a[data_idx] = images
            outs, new_aux = runner(tuple(a), aux, key, True)
            logits = outs[0]
            lse = jax.nn.logsumexp(logits, -1)
            gold = jnp.take_along_axis(logits, labels[:, None], -1)[:, 0]
            return jnp.mean(lse - gold), new_aux

        (loss, new_aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(args)
        new_args, new_vel = [], []
        for i, (a, g, v) in enumerate(zip(args, grads, vel)):
            if i == data_idx:
                new_args.append(a)
                new_vel.append(v)
                continue
            g = g + wd * a
            v = momentum * v - lr * g
            new_args.append(a + v)
            new_vel.append(v)
        return tuple(new_args), new_aux, tuple(new_vel), loss

    jstep = jax.jit(step, donate_argnums=(0, 1, 2))

    vel = tuple(jnp.zeros_like(a) for a in arg_arrays)
    images = jnp.asarray(rng.standard_normal((BATCH, 3, 224, 224)),
                         jnp.float32)
    labels = jnp.asarray(rng.randint(0, 1000, (BATCH,)), jnp.int32)
    key = jax.random.PRNGKey(0)
    return jstep, tuple(arg_arrays), aux_arrays, vel, images, labels, key


def main():
    import jax
    t = time.perf_counter()
    jstep, args, aux, vel, images, labels, key = build_train_step()
    _log('[bench] build+init: %.1fs' % (time.perf_counter() - t))
    t = time.perf_counter()
    for _ in range(WARMUP_STEPS):
        args, aux, vel, loss = jstep(args, aux, vel, images, labels, key)
    jax.block_until_ready(loss)
    _log('[bench] compile+warmup: %.1fs, loss=%.4f' %
         (time.perf_counter() - t, float(loss)))

    t0 = time.perf_counter()
    for _ in range(BENCH_STEPS):
        args, aux, vel, loss = jstep(args, aux, vel, images, labels, key)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    img_s = BENCH_STEPS * BATCH / dt
    print(json.dumps({
        'metric': 'resnet50_train_throughput',
        'value': round(img_s, 2),
        'unit': 'images/sec',
        'vs_baseline': round(img_s / BASELINE_IMG_S, 3),
    }))


if __name__ == '__main__':
    main()
