"""Headline benchmark: ResNet-50 training throughput (images/sec) + MFU.

Mirrors the reference's `train_imagenet.py` perf table config
(docs/how_to/perf.md:150-190, batch 32, synthetic data): one full
training step — forward, softmax CE, backward, mixed-precision
SGD-momentum update (bf16 compute, fp32 master weights via the
registered `mp_sgd_mom_update` op), BatchNorm stat updates — compiled
to a single donated-buffer XLA computation.

vs_baseline divides by the strongest single-GPU reference number:
P100 batch-32 ResNet-50 training at 181.53 img/s (BASELINE.md).

Robustness (round-3 hardening): prints a heartbeat before the first
device touch, probes the backend in a throwaway subprocess (a hung TPU
tunnel can never wedge this process's backend lock), and spreads
retries over the WHOLE bench budget: if the first probes fail it banks
a CPU fallback number immediately, then keeps reprobing the TPU until
MXTPU_BENCH_BUDGET seconds (default 20 min) have elapsed — a tunnel
that recovers mid-run still yields a real device number.

Prints JSON lines {"metric", "value", "unit", "vs_baseline", ...};
the LAST line is authoritative (a banked CPU fallback line may precede
a late real-device line).
"""
import json
import os
import sys
import time

import numpy as np

# P100 batch-32 training rows, docs/how_to/perf.md:150-190 (AlexNet is
# the table's 8x-batch column: batch 256)
BASELINE_IMG_S = {'resnet50': 181.53, 'alexnet': 1869.69,
                  'inceptionv3': 129.98}
# 'resnet50' (the baseline-comparable default), 'alexnet'/'inceptionv3'
# (the other two train_imagenet.py perf-table columns), or 'transformer'
# (the matmul-dominated MFU probe: GPT-style decoder, flash-attention
# Pallas kernel + fused rmsnorm; tpu_capture.sh records both)
MODEL = os.environ.get('MXTPU_BENCH_MODEL', 'resnet50')
BATCH = int(os.environ.get('MXTPU_BENCH_BATCH',
                           '256' if MODEL == 'alexnet' else '32'))
# gradient-memory tradeoff knob (BASELINE.md "Memory-mirroring"); same
# values the executor honors: '1' = full remat, 'dots' = keep matmuls
MIRROR = os.environ.get('MXTPU_BACKWARD_DO_MIRROR',
                        os.environ.get('MXNET_BACKWARD_DO_MIRROR', ''))
MIRROR = '' if MIRROR in ('', '0', 'false', 'False') else MIRROR
# steps fused into one XLA call via lax.scan (in-graph train loop, the
# standard TPU pattern). Each compiled(...) dispatch crosses the axon
# tunnel; at ~ms RTTs a per-step dispatch caps throughput regardless of
# chip speed — measured A/B on 2026-07-31: spc=1 1596 img/s, spc=8
# 2468, spc=32 2552, spc=64 2572 (saturated). 32 balances the gain
# against warmup cost on a flaky tunnel.
STEPS_PER_CALL = int(os.environ.get('MXTPU_BENCH_STEPS_PER_CALL', '32'))
WARMUP_STEPS = 3
INIT_ATTEMPTS = int(os.environ.get('MXTPU_BENCH_INIT_ATTEMPTS', '3'))
INIT_TIMEOUT_S = float(os.environ.get('MXTPU_BENCH_INIT_TIMEOUT', '180'))
INIT_BACKOFF_S = 5.0      # exponential: 5s, 10s, 20s, ... (capped)
INIT_BACKOFF_CAP_S = 60.0
# probe attempts the last init_backend() burned before succeeding or
# banking the CPU fallback — BENCH JSON's 'backend_attempts', so the
# r02/r04 flaky-tunnel shape is visible in the bench history
BACKEND_ATTEMPTS = 0
BUDGET_S = float(os.environ.get('MXTPU_BENCH_BUDGET', '1200'))
REPROBE_TIMEOUT_S = 120.0
REPROBE_SLEEP_S = 45.0
_START = time.perf_counter()

# Peak dense bf16 FLOP/s per chip lives in ONE place —
# mxnet_tpu/telemetry/xla.py — shared by this bench's MFU and the
# telemetry summary's xla.mfu gauge (see _peak_flops below).


def _log(msg):
    print('[bench] ' + msg, file=sys.stderr, flush=True)


def _clear_backends():
    try:
        import jax.extend.backend
        jax.extend.backend.clear_backends()
        return
    except Exception:
        pass
    try:
        from jax._src import xla_bridge
        xla_bridge.backends.cache_clear()
    except Exception:
        pass


def _fault_probe_timeouts():
    """``MXTPU_FAULT_INJECT=backend-probe-timeout:<n>``: the first n
    probe attempts report a timeout (the r02/r04 flaky-tunnel shape),
    exercising the backoff/reprobe path deterministically. Parsed here
    — bench must not import the framework before its backend decision."""
    raw = os.environ.get('MXTPU_FAULT_INJECT', '')
    parts = raw.split(':')
    if len(parts) >= 2 and parts[0] == 'backend-probe-timeout':
        try:
            return int(parts[1])
        except ValueError:
            pass
    return 0


def _probe_subprocess(timeout_s):
    """Probe the default backend in a THROWAWAY subprocess so a hung TPU
    runtime/tunnel can never wedge this process's backend-init lock.
    Returns 'ok', 'error: ...', or 'timeout'."""
    import subprocess
    code = ('import jax; d = jax.devices(); '
            'print("PROBE_OK", d[0].platform, flush=True)')
    try:
        proc = subprocess.Popen([sys.executable, '-c', code],
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)
    except OSError as e:
        return 'error: %s' % e
    t0 = time.perf_counter()
    while True:
        try:
            out, _ = proc.communicate(timeout=10.0)
            if 'PROBE_OK' in (out or ''):
                for ln in out.splitlines():
                    if ln.startswith('PROBE_OK'):
                        parts = ln.split()
                        if len(parts) > 1:
                            return 'ok %s' % parts[1]
                return 'ok'
            tail = (out or '').strip().splitlines()
            return 'error: %s' % (tail[-1] if tail else 'rc=%d'
                                  % proc.returncode)
        except subprocess.TimeoutExpired:
            waited = time.perf_counter() - t0
            _log('  ...probe still initializing (%.0fs)' % waited)
            if waited > timeout_s:
                proc.kill()
                return 'timeout'


def init_backend():
    """Initialize the JAX backend safely. The default platform is probed
    in a subprocess first (with heartbeats + timeout + retries); only a
    healthy backend is then initialized in-process. On persistent failure
    the in-process backend — never touched so far — flips cleanly to CPU.
    Returns (devices, platform_note)."""
    import jax
    global BACKEND_ATTEMPTS
    fault_timeouts = _fault_probe_timeouts()
    for attempt in range(1, INIT_ATTEMPTS + 1):
        BACKEND_ATTEMPTS = attempt
        _log('backend probe attempt %d/%d (timeout %ds)...'
             % (attempt, INIT_ATTEMPTS, INIT_TIMEOUT_S))
        t0 = time.perf_counter()
        if attempt <= fault_timeouts:
            _log('  fault injection: probe timeout forced')
            status = 'timeout'
        else:
            status = _probe_subprocess(INIT_TIMEOUT_S)
        if status.startswith('ok'):
            _log('probe healthy in %.1fs; initializing in-process'
                 % (time.perf_counter() - t0))
            devs = jax.devices()
            _log('backend up: %s' % devs)
            return devs, devs[0].platform
        _log('  probe result: %s' % status)
        if attempt < INIT_ATTEMPTS:
            # short exponential backoff before banking the CPU
            # fallback: a flaky tunnel (r02/r04) often recovers within
            # a minute, and a CPU number costs a whole bench round
            delay = min(INIT_BACKOFF_CAP_S,
                        INIT_BACKOFF_S * (2.0 ** (attempt - 1)))
            _log('  retrying in %.0fs' % delay)
            time.sleep(delay)
    # Fall back to CPU so the harness still yields a (marked) number.
    # Safe: this process has never initialized a backend, so no wedged
    # lock — the config flip takes effect cleanly.
    _log('falling back to CPU backend')
    jax.config.update('jax_platforms', 'cpu')
    _clear_backends()
    try:
        devs = jax.devices()
    except Exception as e:  # noqa: BLE001
        _log('FATAL: cpu fallback failed: %s' % e)
        sys.exit(1)
    _log('cpu backend up: %s' % devs)
    return devs, 'cpu(fallback)'


def _shrink_for_cpu():
    """Shrink the workload so a CPU run (fallback or cpu-only host)
    yields a number quickly instead of risking the harness timeout on a
    CPU-compiled ResNet."""
    global BATCH, WARMUP_STEPS, STEPS_PER_CALL
    if 'MXTPU_BENCH_BATCH' not in os.environ:
        BATCH = 8
        if MODEL == 'transformer':
            os.environ['MXTPU_BENCH_BATCH'] = '1'
    WARMUP_STEPS = 1
    if 'MXTPU_BENCH_STEPS_PER_CALL' not in os.environ:
        STEPS_PER_CALL = 1   # dispatch overhead is irrelevant on CPU
    for k, v in (('MXTPU_BENCH_DMODEL', '256'), ('MXTPU_BENCH_LAYERS', '2'),
                 ('MXTPU_BENCH_SEQ', '256'), ('MXTPU_BENCH_VOCAB', '1024')):
        os.environ.setdefault(k, v)


def build_transformer_step():
    """GPT-style decoder train step: bf16 compute / fp32 masters, causal
    flash attention (ops/pallas_kernels) + fused rmsnorm, SwiGLU-free
    4x MLP, tied CE loss. The matmul-dominated MFU probe — ResNet's
    small-spatial conv gradients cap its MFU; this is the shape the MXU
    is built for."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas_kernels import flash_attention
    from mxnet_tpu.ops.registry import get as get_op

    D = int(os.environ.get('MXTPU_BENCH_DMODEL', '1024'))
    L = int(os.environ.get('MXTPU_BENCH_LAYERS', '8'))
    S = int(os.environ.get('MXTPU_BENCH_SEQ', '1024'))
    V = int(os.environ.get('MXTPU_BENCH_VOCAB', '16384'))
    B = int(os.environ.get('MXTPU_BENCH_BATCH', '8'))
    DH = 128
    H = D // DH

    rng = np.random.RandomState(0)

    def p(*shape, scale=None):
        s = scale if scale is not None else (shape[0] ** -0.5)
        return jnp.asarray((rng.standard_normal(shape) * s)
                           .astype(np.float32))

    masters = [p(V, D, scale=0.02)]                      # embed
    for i in range(L):
        masters += [jnp.ones((D,), jnp.float32),          # ln1
                    p(D, 3 * D), p(D, D),                 # qkv, out
                    jnp.ones((D,), jnp.float32),          # ln2
                    p(D, 4 * D), p(4 * D, D)]             # up, down
    masters += [jnp.ones((D,), jnp.float32), p(D, V, scale=0.02 ** 0.5)]
    masters = tuple(masters)

    def rms(x, g):
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True)
        return (x.astype(jnp.float32) *
                jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * g

    def fwd(params, tokens):
        it = iter(params)
        embed = next(it)
        x = embed[tokens]                                 # (B,S,D) bf16
        for _ in range(L):
            g1, wqkv, wo, g2, wup, wdn = (next(it) for _ in range(6))
            h = rms(x, g1)
            qkv = h @ wqkv
            q, k, v = jnp.split(qkv.reshape(B, S, H, 3 * DH), 3, axis=-1)
            a = flash_attention(q, k, v, causal=True)
            x = x + a.reshape(B, S, D) @ wo
            h = rms(x, g2)
            x = x + jax.nn.gelu(h @ wup) @ wdn
        gf, head = next(it), next(it)
        return rms(x, gf) @ head                          # (B,S,V)

    mp_update = get_op('mp_sgd_mom_update').fn
    attrs = {'lr': 0.01, 'momentum': 0.9, 'wd': 0.0,
             'rescale_grad': 1.0, 'clip_gradient': -1.0}

    def step(masters, aux, vel, tokens, labels, key):
        def loss_fn(bf16_params):
            logits = fwd(bf16_params, tokens).astype(jnp.float32)
            lse = jax.nn.logsumexp(logits, -1)
            gold = jnp.take_along_axis(logits, labels[..., None], -1)[..., 0]
            return jnp.mean(lse - gold), aux

        bf16 = tuple(m.astype(jnp.bfloat16) for m in masters)
        (loss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(bf16)
        new_m, new_v = [], []
        for m, g, v in zip(masters, grads, vel):
            _, nv, m32 = mp_update(attrs, m.astype(jnp.bfloat16), g, v, m)
            new_m.append(m32)
            new_v.append(nv)
        return tuple(new_m), aux, tuple(new_v), loss

    vel = tuple(jnp.zeros_like(m) for m in masters)
    tokens = jnp.asarray(rng.randint(0, V, (B, S)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, V, (B, S)), jnp.int32)
    key = jax.random.PRNGKey(0)
    return step, masters, (), vel, tokens, labels, key


def build_train_step():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.executor import _GraphProgram
    from mxnet_tpu.ops.registry import get as get_op

    zoo_name = {'resnet50': 'resnet50_v1', 'alexnet': 'alexnet',
                'inceptionv3': 'inceptionv3'}[MODEL]
    image = 299 if MODEL == 'inceptionv3' else 224
    data_shape = (BATCH, 3, image, image)
    net = vision.get_model(zoo_name, classes=1000)
    net.hybridize()
    _, sym = net._get_graph(
        type('P', (), {'shape': data_shape,
                       'context': None})())  # placeholder-shaped trace
    prog = _GraphProgram(sym)
    arg_shapes, _, aux_shapes = sym.infer_shape(data=data_shape)
    arg_names, aux_names = prog.arg_names, prog.aux_names

    rng = np.random.RandomState(0)
    data_idx = arg_names.index('data')
    masters = []  # fp32 master weights
    for name, shape in zip(arg_names, arg_shapes):
        masters.append(jnp.asarray(_host_init(name, shape, rng)))
    aux_arrays = tuple(jnp.asarray(_host_init(n, s, rng))
                       for n, s in zip(aux_names, aux_shapes))
    runner = prog.make_runner()
    if MIRROR:
        policy = (jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                  if MIRROR == 'dots' else None)
        runner = jax.checkpoint(runner, policy=policy, static_argnums=(3,))
        _log('backward mirroring ON (%s): forward rematerialized in bwd'
             % MIRROR)
    mp_update = get_op('mp_sgd_mom_update').fn

    # BN-free AlexNet diverges (loss=nan by warmup) at the BN-nets' 0.1:
    # its 9216->4096 FC stack amplifies He-init activations with nothing
    # renormalizing them. 0.01 is the original AlexNet recipe's lr.
    lr = 0.01 if MODEL == 'alexnet' else 0.1
    momentum, wd = 0.9, 1e-4
    attrs = {'lr': lr, 'momentum': momentum, 'wd': wd,
             'rescale_grad': 1.0, 'clip_gradient': -1.0}

    def step(masters, aux, vel, images, labels, key):
        # bf16 working copies of the fp32 masters: the whole fwd+bwd runs
        # on the MXU in bf16; the update runs in fp32 (mp_sgd_mom_update).
        def loss_fn(bf16_args):
            a = list(bf16_args)
            a[data_idx] = images
            # aux (BN running stats) also feed the graph in bf16 — fp32
            # aux would promote activations to fp32 mid-network; the
            # UPDATED stats are stored back as fp32 masters below
            aux_bf16 = tuple(x.astype(jnp.bfloat16) for x in aux)
            outs, new_aux = runner(tuple(a), aux_bf16, key, True)
            new_aux = tuple(x.astype(jnp.float32) for x in new_aux)
            logits = outs[0].astype(jnp.float32)
            lse = jax.nn.logsumexp(logits, -1)
            gold = jnp.take_along_axis(logits, labels[:, None], -1)[:, 0]
            return jnp.mean(lse - gold), new_aux

        bf16_args = tuple(m.astype(jnp.bfloat16) for m in masters)
        (loss, new_aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(bf16_args)
        new_masters, new_vel = [], []
        for i, (m, g, v) in enumerate(zip(masters, grads, vel)):
            if i == data_idx:
                new_masters.append(m)
                new_vel.append(v)
                continue
            _, nv, m32 = mp_update(attrs, m.astype(jnp.bfloat16), g, v, m)
            new_masters.append(m32)
            new_vel.append(nv)
        return tuple(new_masters), new_aux, tuple(new_vel), loss

    vel = tuple(jnp.zeros_like(m) for m in masters)
    images = jnp.asarray(rng.standard_normal(data_shape), jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, 1000, (BATCH,)), jnp.int32)
    key = jax.random.PRNGKey(0)
    return step, tuple(masters), aux_arrays, vel, images, labels, key


def _host_init(name, shape, rng):
    """Host-side (numpy) parameter init by name convention — values only
    need to be numerically sane for a throughput bench."""
    if 'gamma' in name or 'var' in name:
        return np.ones(shape, np.float32)
    if 'beta' in name or 'bias' in name or 'mean' in name:
        return np.zeros(shape, np.float32)
    fan_in = int(np.prod(shape[1:])) if len(shape) > 1 else shape[0]
    std = (2.0 / max(1, fan_in)) ** 0.5
    return (rng.standard_normal(shape) * std).astype(np.float32)


def _analyze_step(compiled):
    """XLA's cost/memory analysis of the compiled step, via the
    telemetry program registrar (mxnet_tpu/telemetry/programs) — the
    same record every framework compile site publishes. Registering as
    a step program also feeds xla.step_flops for the MFU gauge (the
    scan body is counted once by XLA regardless of trip count, so the
    record's flops are per-step already). Returns the analysis dict
    (flops, bytes_accessed, temp_bytes, ... — zeros where the backend
    doesn't report); works with telemetry off too."""
    from mxnet_tpu.telemetry import programs as _programs
    rec = _programs.note_program('bench.train_step', compiled,
                                 step_flops=True)
    # the registrar logs analysis failures at debug; the bench operator
    # must SEE why the headline flops/MFU would be zero
    if not rec['flops']:
        _log('cost_analysis unavailable (flops=0) — MFU and the '
             'per-step flops line will be missing/zero')
    if not rec['temp_bytes']:
        _log('memory_analysis unavailable (temp_bytes=0)')
    return rec


def _peak_flops(device):
    from mxnet_tpu.telemetry.xla import device_peak_flops
    peak, _ = device_peak_flops(device)
    return peak, getattr(device, 'device_kind', '') or ''


def _late_tpu_attempt(remaining_s):
    """The tunnel recovered after we banked a CPU number: run the real
    bench in a fresh subprocess (this process's backend is already CPU)
    and relay its JSON line. Returns the parsed dict or None."""
    import subprocess
    env = dict(os.environ)
    env['MXTPU_BENCH_DIRECT'] = '1'
    _log('reprobe healthy: running device bench in subprocess '
         '(%.0fs left)' % remaining_s)
    try:
        proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                              env=env, capture_output=True, text=True,
                              timeout=max(60.0, remaining_s))
    except Exception as e:  # noqa: BLE001
        _log('late device bench failed: %s' % e)
        return None
    sys.stderr.write(proc.stderr)
    for line in reversed((proc.stdout or '').strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    _log('late device bench produced no JSON (rc=%d)' % proc.returncode)
    return None


def _wrap_health_sentinel(raw_step):
    """The train step + the in-graph health sentinel vector
    (telemetry/health step_stats: param norm, update/param ratio,
    finite flags) computed ONCE PER STEP inside the scan — exactly
    where the MXTPU_HEALTH fused-fit path runs it, so the measured
    overhead reflects W sentinel computations per dispatch, not one.
    Takes the raw (unfused) step; the STEPS_PER_CALL fusion is the
    SAME _wrap_steps_per_call the baseline uses (the A/B must not
    compare differently-fused programs)."""
    from mxnet_tpu.telemetry import health as _health

    def one(m, a, v, images, labels, key):
        m2, a2, v2, loss = raw_step(m, a, v, images, labels, key)
        hv = _health.step_stats((loss,), params=m, new_params=m2)
        return m2, a2, v2, (loss, hv)

    return _wrap_steps_per_call(one)


def _measure_health_overhead(raw_step, masters, aux, vel, images, labels,
                             key, per_step_base):
    """Compile the sentinel-wrapped step (sentinel per scan step, like
    the real fused path) and time it against the base per-dispatch
    time. Returns the JSON-ready dict or None (the probe must never
    cost the headline number — it runs after the main measurement and
    consumes the donated buffers it is handed)."""
    import jax
    try:
        t0 = time.perf_counter()
        step_h = _wrap_health_sentinel(raw_step)
        compiled = jax.jit(step_h, donate_argnums=(0, 1, 2)).lower(
            masters, aux, vel, images, labels, key).compile()
        _log('health-sentinel probe compile: %.1fs'
             % (time.perf_counter() - t0))
        masters, aux, vel, (loss, hv) = compiled(
            masters, aux, vel, images, labels, key)            # warmup
        float(np.asarray(loss))
        from mxnet_tpu import telemetry as _tele
        n = int(min(100, max(5, 8.0 / max(per_step_base, 1e-4))))
        t0 = time.perf_counter()
        for _ in range(n):
            # same per-dispatch wrapper as the baseline loop (span +
            # counter): the comparison must not credit the sentinel
            # with the baseline's telemetry bookkeeping
            with _tele.span('bench.dispatch', 'bench'):
                masters, aux, vel, (loss, hv) = compiled(
                    masters, aux, vel, images, labels, key)
            _tele.counter('fit.steps').inc(STEPS_PER_CALL)
        float(np.asarray(loss))
        per_step_h = (time.perf_counter() - t0) / n
        overhead = 100.0 * (per_step_h - per_step_base) / per_step_base
        _log('health sentinel overhead: %.2f%% (%.4fs vs %.4fs per '
             'dispatch, %d probe steps, sentinel per scan step)'
             % (overhead, per_step_h, per_step_base, n))
        hv_host = np.asarray(hv)
        return {'sentinel_overhead_pct': round(overhead, 2),
                'probe_steps': n,
                'finite': bool(np.all(hv_host[..., 0] != 0))}
    except Exception as e:  # noqa: BLE001 — the probe must never kill
        _log('health overhead probe failed: %s' % e)
        return None


def _wrap_steps_per_call(step):
    """Fuse STEPS_PER_CALL steps into one device call via lax.scan —
    shared by the measuring path and the compile-only probe children,
    which must compile the SAME program or the warm-compile number
    would time a cache miss of a different (unwrapped) computation."""
    if STEPS_PER_CALL <= 1:
        return step
    import jax
    inner = step

    def step(masters, aux, vel, images, labels, key):
        def body(carry, _):
            m, a, v = carry
            m, a, v, loss = inner(m, a, v, images, labels, key)
            return (m, a, v), loss
        (m, a, v), losses = jax.lax.scan(
            body, (masters, aux, vel), None, length=STEPS_PER_CALL)
        # last step's ys — tree_map so a step whose ys is a pytree
        # (the health probe's (loss, sentinel) pair) fuses through the
        # same wrapper; for the plain scalar loss this is losses[-1]
        return m, a, v, jax.tree_util.tree_map(lambda x: x[-1], losses)

    return step


def _warm_compile_subprocess(platform, cache_override=None):
    """Time the train-step compile in a fresh child process with the
    persistent cache on (MXTPU_BENCH_COMPILE_ONLY short-circuits the
    child right after its compile — it never EXECUTES the cache-served
    executable, which jax 0.4.x CPU cannot safely do for conv
    programs). Returns seconds or None."""
    import subprocess
    env = dict(os.environ)
    env['MXTPU_BENCH_COMPILE_ONLY'] = '1'
    env['MXTPU_BENCH_DIRECT'] = '1'   # this process verified the backend
    if cache_override is not None:
        env['MXTPU_COMPILE_CACHE'] = cache_override
    if platform.startswith('cpu'):
        env['JAX_PLATFORMS'] = 'cpu'
    _log('probing warm-start compile in a fresh process...')
    try:
        proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                              env=env, capture_output=True, text=True,
                              timeout=600)
        for line in reversed((proc.stdout or '').strip().splitlines()):
            try:
                return float(json.loads(line)['compile_s'])
            except (ValueError, KeyError, TypeError):
                continue
        _log('warm-compile probe produced no JSON (rc=%d): %s'
             % (proc.returncode, (proc.stderr or '')[-300:]))
    except Exception as e:  # noqa: BLE001 — the probe must never kill
        _log('warm-compile probe failed: %s' % e)
    return None


def run_infer_bench(platform, kind):
    """ResNet-50 inference throughput through the REAL Module.predict
    API: the fused window path (module/fused_eval.py, one dispatch +
    one fetch per W batches) vs the per-batch reference path
    (MXTPU_FUSED_EVAL=0). bf16 compute via a Cast at the input —
    type inference makes every downstream parameter bf16, mirroring
    the training bench's compute dtype. Returns the JSON-ready dict
    (both numbers printed; the fused one is the headline)."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.config import flags as _flags
    from mxnet_tpu.gluon.model_zoo import vision

    # a window of 8 keeps the synthetic set (2 windows) small enough to
    # stage on the host while still amortizing dispatch 8x; the CPU
    # fallback keeps its auto window (4) — dispatch is not its problem
    saved_w = os.environ.get('MXTPU_EVAL_STEPS_PER_CALL')
    if not platform.startswith('cpu'):
        os.environ.setdefault('MXTPU_EVAL_STEPS_PER_CALL', '8')
    _flags.reload('MXTPU_EVAL_STEPS_PER_CALL')
    from mxnet_tpu.module.fused_eval import _eval_window
    W = _eval_window()
    batch = BATCH
    # CPU fallback: smaller spatial + one window per pass — the CPU
    # number is already marked non-config-comparable, and fwd compute
    # (not dispatch) dominates there anyway
    cpu = platform.startswith('cpu')
    image = 112 if cpu else 224
    n = (1 if cpu else 2) * W * batch
    _log('building resnet50 inference module (bf16, batch %d, W=%d)...'
         % (batch, W))
    net = vision.get_model('resnet50_v1', classes=1000)
    net.hybridize()
    data_shape = (batch, 3, image, image)
    _, sym = net._get_graph(
        type('P', (), {'shape': data_shape, 'context': None})())
    sym_bf = sym(data=mx.sym.Cast(mx.sym.Variable('data'),
                                  dtype='bfloat16'))
    ctx = mx.tpu() if platform.startswith('tpu') else mx.cpu()
    mod = mx.mod.Module(sym_bf, label_names=[], context=ctx)
    rng = np.random.RandomState(0)
    X = rng.standard_normal((n, 3, image, image)).astype(np.float32)
    it = mx.io.NDArrayIter(X, None, batch_size=batch)
    mod.bind(data_shapes=it.provide_data, for_training=False)
    mod.init_params()

    def timed_predict():
        it.reset()
        t0 = time.perf_counter()
        out = mod.predict(it, reset=False)
        # host fetch = true barrier (per-batch predict is fully async;
        # the fused path is already host-resident by construction)
        np.asarray(out.asnumpy())
        return n / (time.perf_counter() - t0)

    results = {}
    saved_fe = os.environ.get('MXTPU_FUSED_EVAL')
    try:
        for label, flag in (('fused', '1'), ('per_batch', '0')):
            os.environ['MXTPU_FUSED_EVAL'] = flag
            _flags.reload('MXTPU_FUSED_EVAL')
            t = time.perf_counter()
            timed_predict()       # warmup: compiles this path's program
            _log('infer %s warmup: %.1fs' % (label,
                                             time.perf_counter() - t))
            results[label] = timed_predict()
            _log('infer %s: %.2f img/s' % (label, results[label]))
    finally:
        # restore the caller's flags exactly (an explicit
        # MXTPU_FUSED_EVAL=0 opt-out must survive this A/B, including
        # into any late-reprobe child that inherits os.environ)
        for var, saved in (('MXTPU_FUSED_EVAL', saved_fe),
                           ('MXTPU_EVAL_STEPS_PER_CALL', saved_w)):
            if saved is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = saved
            _flags.reload(var)

    out = {
        'metric': 'resnet50_infer_throughput_bf16',
        'value': round(results['fused'], 2),
        'unit': 'images/sec',
        'per_batch_value': round(results['per_batch'], 2),
        'speedup_vs_per_batch': round(results['fused']
                                      / max(results['per_batch'], 1e-9), 3),
        'batch': batch,
        'eval_steps_per_call': W,
        'device': kind or platform,
        'platform': platform,
    }
    if platform.startswith('cpu'):
        out['note'] = ('cpu run: per-batch dispatch overhead is noise '
                       'next to compute, so the window speedup only '
                       'shows on a real (tunneled) device')
    return out


def run_serving_bench(platform):
    """Closed-loop load generator against the in-process serving plane
    (mxnet_tpu/serving, ISSUE 13): a ServingEngine over a small MLP
    with the bucket ladder pre-warmed, a DynamicBatcher in front, and
    K client threads each running a closed request loop (send 1-4
    rows, wait for the answer, repeat) — no HTTP, so the numbers
    measure queue+coalesce+dispatch+split, not socket overhead.
    Banks serving_p50_ms / serving_p99_ms / serving_throughput_rps /
    pad_fraction (tools/bench_diff.py gates the p99 at 10%)."""
    import threading as _threading
    import mxnet_tpu as mx
    from mxnet_tpu.serving import DynamicBatcher, ServingEngine

    clients = int(os.environ.get('MXTPU_BENCH_SERVE_CLIENTS', '4'))
    per_client = int(os.environ.get('MXTPU_BENCH_SERVE_REQS', '50'))
    max_batch = int(os.environ.get('MXTPU_BENCH_SERVE_MAX_BATCH', '16'))
    hidden = 64
    _log('serving bench: %d clients x %d closed-loop requests, '
         'bucket ladder up to %d...' % (clients, per_client, max_batch))
    data = mx.sym.Variable('data')
    fc1 = mx.sym.FullyConnected(data, num_hidden=hidden, name='srv_fc1')
    act = mx.sym.Activation(fc1, act_type='relu', name='srv_relu')
    fc2 = mx.sym.FullyConnected(act, num_hidden=8, name='srv_fc2')
    sym = mx.sym.SoftmaxOutput(fc2, name='softmax')
    ctx = mx.tpu() if platform.startswith('tpu') else mx.cpu()
    mod = mx.mod.Module(sym, context=ctx)
    mod.bind(data_shapes=[('data', (max_batch, 16))], for_training=False)
    mod.init_params()
    engine = ServingEngine(mod, max_batch=max_batch)
    t = time.perf_counter()
    engine.warmup()
    warm_s = time.perf_counter() - t
    batcher = DynamicBatcher(engine, max_wait_ms=2.0).start()

    lats, errors = [], [0]
    lock = _threading.Lock()

    def client(seed):
        rng = np.random.RandomState(seed)
        mine = []
        for _ in range(per_client):
            rows = int(rng.randint(1, 5))
            x = rng.standard_normal((rows, 16)).astype(np.float32)
            t0 = time.perf_counter()
            try:
                batcher.predict([x], timeout=60)
            except Exception:  # noqa: BLE001 — counted, never fatal
                with lock:
                    errors[0] += 1
                continue
            mine.append((time.perf_counter() - t0) * 1e3)
        with lock:
            lats.extend(mine)

    threads = [_threading.Thread(target=client, args=(1000 + i,))
               for i in range(clients)]
    t0 = time.perf_counter()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    wall = time.perf_counter() - t0
    # read the ledgers AFTER close(): it joins the dispatcher and the
    # fetch pool, so the final batch's stage entry has landed and no
    # thread mutates the deques mid-iteration
    batcher.close()
    log = list(batcher.dispatch_log)
    queue_waits = list(batcher.queue_wait_log)
    stage_log = list(batcher.stage_log)
    if not lats:
        raise RuntimeError('serving bench produced no successful requests')
    total_rows = sum(r for r, _, _ in log)
    bucket_rows = sum(b for _, b, _ in log)

    def _stage_p50(key):
        vals = [s[key] for s in stage_log if s.get(key) is not None]
        return round(float(np.percentile(vals, 50)), 3) if vals else None

    out = {
        'serving_p50_ms': round(float(np.percentile(lats, 50)), 3),
        'serving_p99_ms': round(float(np.percentile(lats, 99)), 3),
        'serving_throughput_rps': round(len(lats) / wall, 2),
        # per-stage breakdown (the tracing plane's host-measured
        # decomposition; queue wait gated by tools/bench_diff.py)
        'serving_queue_wait_p50_ms': round(
            float(np.percentile(queue_waits, 50)), 3)
        if queue_waits else None,
        'serving_stage_p50_ms': {
            'coalesce': _stage_p50('coalesce_ms'),
            'pad': _stage_p50('pad_ms'),
            'dispatch': _stage_p50('dispatch_ms'),
            'fetch': _stage_p50('fetch_ms'),
            'split': _stage_p50('split_ms'),
        },
        'pad_fraction': round((bucket_rows - total_rows)
                              / float(max(bucket_rows, 1)), 4),
        'requests': len(lats),
        'errors': errors[0],
        'clients': clients,
        'dispatches': len(log),
        'mean_batch': round(total_rows / float(max(len(log), 1)), 2),
        'coalesced_dispatches': sum(1 for _, _, n in log if n > 1),
        'max_batch': max_batch,
        'warmup_s': round(warm_s, 2),
    }
    _log('serving: %.1f req/s, p50 %.2f ms, p99 %.2f ms, '
         'mean batch %.1f over %d dispatches (%d coalesced), '
         'pad %.1f%%'
         % (out['serving_throughput_rps'], out['serving_p50_ms'],
            out['serving_p99_ms'], out['mean_batch'], out['dispatches'],
            out['coalesced_dispatches'], 100 * out['pad_fraction']))
    stages = out['serving_stage_p50_ms']
    _log('serving stages p50: queue %s ms, %s'
         % (out['serving_queue_wait_p50_ms'],
            ', '.join('%s %s ms' % (k, stages[k])
                      for k in ('coalesce', 'pad', 'dispatch', 'fetch',
                                'split'))))
    return out


def run_fused_window_ab(platform):
    """Donation + BN-one-pass A/B (ISSUE 12) through the REAL
    Module.fit fused window on a conv+BatchNorm net: the 'pre' arm
    rebuilds the pre-PR program (MXTPU_FUSED_DONATE=0,
    MXTPU_BN_ONEPASS=0 — undonated carry, two-pass stats), the 'tuned'
    arm runs the shipped defaults. Per arm: one warm fit (compiles the
    window), two timed epochs, then the window program's
    temp/live/alias bytes off the registrar gauges and the
    update/upload overlap off the fused_fit.overlap_ms histogram.
    Banks gracefully on the CPU fallback (the bytes + overlap numbers
    are real everywhere; the throughput delta only means something on
    a device backend, noted)."""
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry as _tele
    from mxnet_tpu.config import flags as _flags

    saved = {v: os.environ.get(v) for v in
             ('MXTPU_FUSED_DONATE', 'MXTPU_BN_ONEPASS',
              'MXTPU_FIT_STEPS_PER_CALL')}
    os.environ['MXTPU_FIT_STEPS_PER_CALL'] = '4'
    _flags.reload('MXTPU_FIT_STEPS_PER_CALL')
    batch, windows_per_epoch = 8, 4
    n = batch * 4 * windows_per_epoch
    ctx = mx.tpu() if platform.startswith('tpu') else mx.cpu()
    res = {}
    try:
        for arm, (don, bn) in (('pre', ('0', '0')),
                               ('tuned', ('1', '1'))):
            os.environ['MXTPU_FUSED_DONATE'] = don
            os.environ['MXTPU_BN_ONEPASS'] = bn
            _flags.reload('MXTPU_FUSED_DONATE')
            _flags.reload('MXTPU_BN_ONEPASS')
            mx.random.seed(13)
            rng = np.random.RandomState(13)
            # distinct symbol names per arm -> distinct program records
            name = 'fwab_%s' % arm
            d = mx.sym.Variable('data')
            h = d
            for i in range(3):
                h = mx.sym.Convolution(h, num_filter=32, kernel=(3, 3),
                                       pad=(1, 1),
                                       name='%s_conv%d' % (name, i))
                h = mx.sym.BatchNorm(h, name='%s_bn%d' % (name, i))
                h = mx.sym.Activation(h, act_type='relu')
            h = mx.sym.FullyConnected(mx.sym.Flatten(h), num_hidden=16,
                                      name='%s_fc' % name)
            sym = mx.sym.SoftmaxOutput(h, name=name)
            X = rng.standard_normal((n, 3, 16, 16)).astype(np.float32)
            y = (rng.rand(n) * 16).astype(int).astype(np.float32)
            it = mx.io.NDArrayIter(X, y, batch_size=batch, shuffle=False,
                                   label_name='%s_label' % name)
            mod = mx.mod.Module(sym, context=ctx,
                                label_names=('%s_label' % name,))
            okw = dict(optimizer='sgd',
                       optimizer_params=(('learning_rate', 0.01),
                                         ('momentum', 0.9)),
                       eval_metric='acc')
            t = time.perf_counter()
            mod.fit(it, num_epoch=1, **okw)      # compile + warm
            _log('fused-window A/B %s warmup: %.1fs'
                 % (arm, time.perf_counter() - t))
            t0 = time.perf_counter()
            mod.fit(it, begin_epoch=1, num_epoch=3, **okw)
            dt = time.perf_counter() - t0
            snap = _tele.snapshot() if _tele.enabled() else {}
            g = snap.get('gauges', {})
            pfx = 'program.fused_fit.window[%s].' % name
            hist = snap.get('histograms', {}).get('fused_fit.overlap_ms')
            res[arm] = {
                'img_s': round(2 * n / dt, 2),
                'temp_bytes': int(g.get(pfx + 'temp_bytes', 0)) or None,
                'live_bytes': int(g.get(pfx + 'live_bytes', 0)) or None,
                'alias_bytes': int(g.get(pfx + 'alias_bytes', 0)) or None,
                'overlap_ms_p50': round(hist['p50'], 3)
                if hist and hist.get('count') else None}
            _log('fused-window A/B %s: %.2f img/s, temp=%s live=%s '
                 'overlap_p50=%s ms'
                 % (arm, res[arm]['img_s'], res[arm]['temp_bytes'],
                    res[arm]['live_bytes'], res[arm]['overlap_ms_p50']))
    finally:
        for var, old in saved.items():
            if old is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = old
            _flags.reload(var)
    pre, tuned = res['pre'], res['tuned']
    ab = {'batch': batch, 'pre': pre, 'tuned': tuned,
          'speedup': round(tuned['img_s'] / max(pre['img_s'], 1e-9), 3)}
    if pre['live_bytes'] and tuned['live_bytes']:
        ab['live_bytes_drop_pct'] = round(
            100.0 * (pre['live_bytes'] - tuned['live_bytes'])
            / pre['live_bytes'], 1)
    if pre['temp_bytes'] and tuned['temp_bytes']:
        ab['temp_bytes_drop_pct'] = round(
            100.0 * (pre['temp_bytes'] - tuned['temp_bytes'])
            / pre['temp_bytes'], 1)
    if platform.startswith('cpu'):
        ab['note'] = ('cpu arm: the bytes/overlap evidence is real; '
                      'the img/s delta only means something on a '
                      'device backend')
    return ab


def run_sharded_update_ab(platform):
    """Sharded-vs-replicated weight-update A/B (MXTPU_SHARDED_UPDATE,
    arXiv:2004.13336) through the REAL Module.fit fused window over a
    dp mesh of all local devices. Only meaningful at dp > 1 (returns
    None otherwise — the ZeRO layout is a documented no-op at dp=1).
    Per arm: one warm fit (compiles the window), then two timed
    epochs; the per-device optimizer-state footprint comes off the
    update.opt_state_bytes_per_device gauge the loop publishes, and
    the update collectives' traffic off the roofline's per-opcode
    accounting for the sharded arm's window program. MXTPU_BENCH_AB_*
    env knobs size the probe model."""
    import jax
    ndev = len(jax.devices())
    if ndev < 2:
        _log('sharded-update A/B skipped: dp=1 (single device)')
        return None
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry as _tele
    from mxnet_tpu.config import flags as _flags

    hidden = int(os.environ.get('MXTPU_BENCH_AB_HIDDEN', '512'))
    feat = int(os.environ.get('MXTPU_BENCH_AB_FEATURES', '64'))
    batch = 8 * ndev
    windows_per_epoch = 4
    saved = {v: os.environ.get(v) for v in
             ('MXTPU_SHARDED_UPDATE', 'MXTPU_FIT_STEPS_PER_CALL')}
    os.environ['MXTPU_FIT_STEPS_PER_CALL'] = '4'
    _flags.reload('MXTPU_FIT_STEPS_PER_CALL')
    n = batch * 4 * windows_per_epoch
    ctx_fn = mx.tpu if platform.startswith('tpu') else mx.cpu
    ctxs = [ctx_fn(i) for i in range(ndev)]
    res = {}
    try:
        for arm, flag in (('replicated', '0'), ('sharded', '1')):
            os.environ['MXTPU_SHARDED_UPDATE'] = flag
            _flags.reload('MXTPU_SHARDED_UPDATE')
            mx.random.seed(11)
            rng = np.random.RandomState(11)
            # distinct symbol names per arm -> distinct program records
            # in the registrar/roofline (the merge rule would otherwise
            # keep whichever variant parsed larger)
            name = 'ab_%s' % arm
            data = mx.sym.Variable('data')
            h = mx.sym.Activation(mx.sym.FullyConnected(
                data, num_hidden=hidden, name='%s_fc1' % name),
                act_type='relu')
            h = mx.sym.Activation(mx.sym.FullyConnected(
                h, num_hidden=hidden, name='%s_fc2' % name),
                act_type='relu')
            sym = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
                h, num_hidden=16, name='%s_fc3' % name), name=name)
            X = rng.standard_normal((n, feat)).astype(np.float32)
            y = (rng.rand(n) * 16).astype(int).astype(np.float32)
            it = mx.io.NDArrayIter(X, y, batch_size=batch, shuffle=False,
                                   label_name='%s_label' % name)
            mod = mx.mod.Module(sym, context=ctxs,
                                label_names=('%s_label' % name,))
            okw = dict(optimizer='sgd',
                       optimizer_params=(('learning_rate', 0.01),
                                         ('momentum', 0.9)),
                       kvstore='device', eval_metric='acc')
            t = time.perf_counter()
            mod.fit(it, num_epoch=1, **okw)      # compile + warm
            _log('sharded-update A/B %s warmup: %.1fs'
                 % (arm, time.perf_counter() - t))
            t0 = time.perf_counter()
            mod.fit(it, begin_epoch=1, num_epoch=3, **okw)
            dt = time.perf_counter() - t0
            g = _tele.snapshot()['gauges'] if _tele.enabled() else {}
            loop = mod.__dict__.get('_fused_fit_cache')
            res[arm] = {
                'img_s': round(2 * n / dt, 2),
                'opt_state_bytes_per_device':
                    int(g['update.opt_state_bytes_per_device'])
                    if 'update.opt_state_bytes_per_device' in g else None,
                'engaged': bool(loop is not None
                                and loop[1]._zero is not None)}
            _log('sharded-update A/B %s: %.2f img/s, opt state '
                 '%s B/device' % (arm, res[arm]['img_s'],
                                  res[arm]['opt_state_bytes_per_device']))
        comm = _tele.roofline.comm_bytes_by_op('fused_fit.window[ab_sharded')
        upd_comm = sum(v for k, v in comm.items()
                       if k.startswith(('reduce-scatter', 'all-gather')))
    finally:
        for var, old in saved.items():
            if old is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = old
            _flags.reload(var)
    r0, r1 = res['replicated'], res['sharded']
    ab = {'dp': ndev, 'batch': batch, 'hidden': hidden,
          'replicated_img_s': r0['img_s'], 'sharded_img_s': r1['img_s'],
          'sharded_speedup': round(r1['img_s'] / max(r0['img_s'], 1e-9), 3),
          'sharded_engaged': r1['engaged'],
          'opt_state_bytes_per_device': r1['opt_state_bytes_per_device'],
          'opt_state_bytes_per_device_replicated':
              r0['opt_state_bytes_per_device']}
    if upd_comm:
        # per-step bytes the sharded update moves between chips
        # (reduce-scatter'd grads + all-gather'd params; CPU lowerings
        # without the reduce-scatter pass show the all-gather half)
        ab['update_comm_bytes'] = round(upd_comm, 1)
    return ab


def _telemetry_breakdown(device, step_ms=None):
    """The dispatch/compile breakdown + peak device bytes from the
    telemetry registry, as a JSON-ready dict (None when telemetry is
    off or empty) — BENCH_*.json carries this from this round on.
    ``step_ms`` is the measured per-step wall time — the roofline's
    denominator (the registry can only see per-DISPATCH spans here,
    which cover STEPS_PER_CALL steps each)."""
    try:
        from mxnet_tpu import telemetry as _tele
        if not _tele.enabled():
            return None
        _tele.xla.sample_memory(device)
        snap = _tele.snapshot()
        tel = {}
        c = snap['counters']
        if c.get('xla.compiles'):
            tel['compiles'] = int(c['xla.compiles'])
            tel['compile_secs'] = round(c.get('xla.compile_secs', 0.0), 3)
        if c.get('xla.cache_hits'):
            # compiles served from the MXTPU_COMPILE_CACHE directory
            tel['cache_hits'] = int(c['xla.cache_hits'])
            tel['cache_saved_secs'] = round(
                c.get('xla.cache_saved_secs', 0.0), 3)
        h = snap['histograms'].get('bench.dispatch')
        if h and h['count']:
            tel['dispatch_ms'] = {k: round(h[k], 3)
                                  for k in ('p50', 'p95', 'max')}
        g = snap['gauges']
        if 'xla.peak_bytes_in_use' in g:
            tel['peak_device_bytes'] = int(g['xla.peak_bytes_in_use'])
        if 'xla.bytes_in_use' in g:
            tel['live_device_bytes'] = int(g['xla.bytes_in_use'])
        # sharded weight update (ISSUE 9): the per-device optimizer-
        # state footprint the fused loop published, when a Module fit
        # ran in this process BEFORE this fold (the A/B probe runs
        # after it, so its gauges land only in out['sharded_update_ab'])
        if 'update.opt_state_bytes_per_device' in g:
            tel['opt_state_bytes_per_device'] = \
                int(g['update.opt_state_bytes_per_device'])
            tel['sharded_update'] = bool(g.get('update.sharded'))
        # quantized gradient collectives (ISSUE 17): wire bytes per
        # sync step + ratio, with the measured/modeled provenance the
        # gauges carry — bench_diff gates the byte count
        if 'comm.bytes_on_wire_per_step' in g:
            tel['bytes_on_wire_per_step'] = \
                int(g['comm.bytes_on_wire_per_step'])
            if g.get('comm.compression_ratio') is not None:
                tel['compression_ratio'] = \
                    float(g['comm.compression_ratio'])
            if g.get('comm.mode'):
                tel['compress_mode'] = g['comm.mode']
            if g.get('comm.bytes_src'):
                tel['comm_bytes_src'] = g['comm.bytes_src']
        # training-health counts (ISSUE 4): anomalies / non-finite
        # steps seen by the sentinels, when MXTPU_HEALTH ran
        hc = {n[len('health.'):]: int(v) for n, v in c.items()
              if n.startswith('health.')}
        if hc:
            tel['health'] = hc
        # cluster aggregation (ISSUE 5): the last sync round's per-host
        # gauges + straggler attribution, when MXTPU_TELEMETRY_SYNC_EVERY
        # ran; plus the live endpoint's port when one is serving
        clus = _tele.cluster.snapshot_cluster()
        if clus:
            tel['cluster'] = clus
        live_port = _tele.serve.port()
        if live_port is not None:
            tel['live_endpoint_port'] = live_port
        # per-program cost attribution (ISSUE 3): FLOPs/bytes per
        # compiled program — bench.train_step plus whatever the Module
        # paths compiled — alongside the top-line numbers
        progs = _tele.programs.snapshot_programs()
        if progs:
            tel['programs'] = {
                n: {'flops': r['flops'],
                    'bytes_accessed': r['bytes_accessed'],
                    'temp_bytes': r['temp_bytes'],
                    'compiles': r['compiles'],
                    'dispatches': r['dispatches']}
                for n, r in sorted(progs.items())}
        # roofline attribution (ISSUE 7): per-layer class + achieved/
        # peak placement and the collective accounting, published to
        # gauges/JSONL by summarize() and folded here (layers truncated
        # to the summary block's TOP_N — the JSONL record keeps all)
        roof = _tele.roofline.summarize(step_time_ms=step_ms)
        if roof:
            top_n = _tele.roofline.TOP_N
            tel['roofline'] = dict(roof, layers=roof['layers'][:top_n],
                                   n_layers=len(roof['layers']))
        # memory attribution (ISSUE 19): per-layer HBM shares + the
        # headroom/steps-to-OOM forecast — same truncation treatment;
        # per-program peak bytes ride the programs dict above
        mem = _tele.memory.summarize()
        if mem:
            lay = mem.get('layers') or []
            tel['memory'] = dict(mem, layers=lay[:_tele.memory.TOP_N],
                                 n_layers=len(lay))
            if mem.get('peaks') and tel.get('programs'):
                for n, pk in mem['peaks'].items():
                    if n in tel['programs']:
                        tel['programs'][n]['peak_bytes'] = int(pk)
        # goodput attribution (ISSUE 16): where this process's wall-
        # clock went, bucketed — AFTER roofline.summarize so the comm
        # bucket reads the just-published provenance-labeled share
        good = _tele.goodput.current()
        if good:
            tel['goodput'] = good
        # step timeline (ISSUE 20): the per-step phase decomposition
        # (compute / collective-wait / io / host-side shares) —
        # bench_diff gates the host-side share (host_overhead_pct)
        pb = _tele.timeline.phase_breakdown()
        if pb:
            tel['step_phase_breakdown'] = pb
            tl = _tele.timeline.summarize()
            if tl:
                tel['timeline'] = tl
        return tel or None
    except Exception as e:  # noqa: BLE001 — the bench number must survive
        _log('telemetry fold-in failed: %s' % e)
        return None


def main():
    _log('python up, pid=%d — probing backend before any device work'
         % os.getpid())
    # telemetry rides every bench run (ISSUE 1): the compile/dispatch
    # breakdown and peak device bytes fold into the emitted JSON below.
    # setdefault: an explicit MXTPU_TELEMETRY=0 still wins.
    import tempfile
    os.environ.setdefault('MXTPU_TELEMETRY', '1')
    os.environ.setdefault('MXTPU_TELEMETRY_PATH',
                          os.path.join(tempfile.gettempdir(),
                                       'bench_telemetry.jsonl'))
    # roofline attribution rides every bench run (ISSUE 7): per-layer
    # achieved-vs-peak classification + collective accounting fold into
    # the emitted JSON below. setdefault: an explicit =0 still wins.
    os.environ.setdefault('MXTPU_ROOFLINE', '1')
    # memory plane rides every bench run (ISSUE 19): per-layer HBM
    # attribution + headroom forecast fold into the emitted JSON below,
    # and bench_diff gates the headroom. setdefault: an explicit =0
    # still wins.
    os.environ.setdefault('MXTPU_MEMORY', '1')
    # step timeline rides every bench run (ISSUE 20): the phase
    # decomposition folds into the emitted JSON below and bench_diff
    # gates the host-side share. setdefault: an explicit =0 still wins.
    os.environ.setdefault('MXTPU_TIMELINE', '1')
    if os.environ.get('MXTPU_BENCH_DIRECT'):
        # child of a successful late reprobe: init the default backend
        # straight away (the parent just verified it is healthy)
        import jax
        devices = jax.devices()
        platform = devices[0].platform
        _log('direct mode: backend %s' % devices)
    else:
        devices, platform = init_backend()
    if platform.startswith('cpu'):
        _shrink_for_cpu()   # single decision point for every CPU path
    else:
        # persistent XLA compile cache rides every DEVICE bench run
        # (ISSUE 2): a warm start skips the ~26s ResNet compile, and
        # the cold/warm pair below quantifies it. Device platforms
        # only: on jax 0.4.x the CPU backend's conv custom-call thunks
        # do not survive executable deserialization — a cache-served
        # ResNet step segfaults a few iterations in (measured here;
        # trivial programs round-trip fine). setdefault: an explicit
        # MXTPU_COMPILE_CACHE — including '' — still wins.
        os.environ.setdefault('MXTPU_COMPILE_CACHE',
                              os.path.join(tempfile.gettempdir(),
                                           'mxtpu_bench_xla_cache'))
    import jax

    if os.environ.get('MXTPU_BENCH_COMPILE_ONLY'):
        # warm-compile probe child (_warm_compile_subprocess): build the
        # same step, time ONE compile — served from MXTPU_COMPILE_CACHE
        # when populated — and exit without executing anything
        if MODEL == 'transformer':
            step, masters, aux, vel, images, labels, key = \
                build_transformer_step()
        else:
            step, masters, aux, vel, images, labels, key = build_train_step()
        step = _wrap_steps_per_call(step)
        t = time.perf_counter()
        jax.jit(step, donate_argnums=(0, 1, 2)).lower(
            masters, aux, vel, images, labels, key).compile()
        print(json.dumps({'compile_s': round(time.perf_counter() - t, 2)}),
              flush=True)
        return

    t = time.perf_counter()
    if MODEL == 'transformer':
        _log('building GPT-style decoder train step '
             '(bf16, flash attention)...')
        step, masters, aux, vel, images, labels, key = \
            build_transformer_step()
        tokens_per_batch = int(images.shape[0] * images.shape[1])
    else:
        _log('building %s train step (bf16 compute, fp32 masters)...'
             % MODEL)
        step, masters, aux, vel, images, labels, key = build_train_step()
        tokens_per_batch = None
    _log('build+init: %.1fs' % (time.perf_counter() - t))

    raw_step = step   # pre-fusion form: the health probe re-fuses it
    if STEPS_PER_CALL > 1:         # with a sentinel inside each step
        step = _wrap_steps_per_call(step)
        _log('fusing %d steps per device call (lax.scan)' % STEPS_PER_CALL)

    from mxnet_tpu import telemetry as _tele

    t = time.perf_counter()
    _log('compiling (first compile can take 20-40s)...')
    jstep = jax.jit(step, donate_argnums=(0, 1, 2))
    lowered = jstep.lower(masters, aux, vel, images, labels, key)
    compiled = lowered.compile()
    compile_cold_s = time.perf_counter() - t
    step_analysis = _analyze_step(compiled)
    # XLA cost analysis counts a scan (while-loop) body ONCE regardless
    # of trip count (verified: identical flops at 1 vs 8 steps/call), so
    # scale to per-dispatch flops here (the registrar already fed the
    # per-step value to the MFU gauge)
    flops_per_step = step_analysis['flops'] * STEPS_PER_CALL
    temp_bytes = step_analysis['temp_bytes']
    _log('compile: %.1fs, step flops=%.3e, xla temp=%.1f MiB'
         % (compile_cold_s, flops_per_step, temp_bytes / 2**20))

    # cold vs warm compile (MXTPU_COMPILE_CACHE): a fresh child process
    # builds the SAME step and times one compile, now served from the
    # persistent cache — exactly what a restart pays. A subprocess
    # keeps this process clean: no jax.clear_caches() mid-run, and a
    # cache-deserialized executable is never executed here. On a warm
    # START the 'cold' number above is itself cache-served; the
    # cache_hits counter in the telemetry fold-in disambiguates.
    cache_dir = os.environ.get('MXTPU_COMPILE_CACHE')
    compile_warm_s = None
    cache_cold_s = compile_cold_s
    served_from_cache = None
    if cache_dir and not platform.startswith('cpu'):
        # device runtimes are single-tenant: a concurrent probe child
        # would contend with THIS process's chip claim (and can deepen
        # a wedged tunnel). The warm number is this run's own compile
        # on the NEXT bench invocation — cache_hits marks a served one,
        # so the BENCH_*.json history carries the cold/warm pair across
        # runs instead of within one.
        try:
            served_from_cache = bool(
                _tele.snapshot()['counters'].get('xla.cache_hits', 0))
        except Exception:  # noqa: BLE001
            served_from_cache = None
        if served_from_cache:
            _log('train-step compile served from the persistent cache '
                 '(%.1fs)' % compile_cold_s)
    elif platform.startswith('cpu'):
        # CPU run: the measuring process keeps the cache OFF (see the
        # segfault note above), but a cold+warm pair of compile-only
        # children against a scratch dir still quantifies the cache
        probe_dir = tempfile.mkdtemp(prefix='mxtpu_cc_probe_')
        try:
            c = _warm_compile_subprocess(platform,
                                         cache_override=probe_dir)
            if c is not None:
                cache_cold_s = c
                compile_warm_s = _warm_compile_subprocess(
                    platform, cache_override=probe_dir)
        finally:
            # the scratch dir holds tens of MB of serialized ResNet
            # executables per run — never leave it behind
            import shutil
            shutil.rmtree(probe_dir, ignore_errors=True)
    if compile_warm_s is not None:
        _log('compile with persistent cache: cold %.1fs -> warm %.1fs '
             '(fresh processes)' % (cache_cold_s, compile_warm_s))

    t = time.perf_counter()
    warm_t0 = t
    warm_losses = []
    for _ in range(WARMUP_STEPS):
        masters, aux, vel, loss = compiled(
            masters, aux, vel, images, labels, key)
        # bench drives the raw compiled object, so the registrar's
        # wrapper never sees these dispatches — count them explicitly
        # or the bench.train_step program record reports dispatches=0
        _tele.programs.note_dispatch('bench.train_step')
        warm_losses.append(loss)   # scalar handles: banked post-barrier
    # sync via host fetch: on tunneled runtimes block_until_ready can
    # return before the chain drains; a device->host copy cannot
    loss_val = float(np.asarray(loss))
    warmup_dt = time.perf_counter() - t
    _log('warmup (%d steps): %.1fs, loss=%.4f'
         % (WARMUP_STEPS, warmup_dt, loss_val))

    # Scale the measured run to ~10-30s of wall clock.
    per_step = max(1e-4, warmup_dt / WARMUP_STEPS)
    bench_steps = int(min(200, max(10, 15.0 / per_step)))
    if platform.startswith('cpu'):
        bench_steps = min(bench_steps, 5)   # part of the CPU shrink
    _log('measuring %d steps...' % bench_steps)
    bench_losses = []
    t0 = time.perf_counter()
    for _ in range(bench_steps):
        # span = host-side dispatch cost per device call (the tunnel-RTT
        # breakdown); device compute overlaps asynchronously behind it
        with _tele.span('bench.dispatch', 'bench'):
            masters, aux, vel, loss = compiled(
                masters, aux, vel, images, labels, key)
        _tele.programs.note_dispatch('bench.train_step')  # see warmup
        # feeds the xla.mfu estimate together with note_step_flops above
        _tele.counter('fit.steps').inc(STEPS_PER_CALL)
        if _tele.timeline.enabled():
            # feeds the step-phase ledger so the timeline fold below
            # can decompose the step (dispatch share + wall per step)
            _tele.timeline.note_step(STEPS_PER_CALL)
        bench_losses.append(loss)
    float(np.asarray(loss))  # host fetch = true barrier (see warmup)
    dt = time.perf_counter() - t0

    # run-ledger feed (ISSUE 15): bank the warmup + measured loss
    # trajectory as `scalars` records. Dispatch is async, so per-call
    # enqueue clocks would bunch at the loop head — timestamps are
    # amortized evenly over each phase's measured wall time instead
    # (only deltas matter to time_to_loss). Fetched AFTER the barrier:
    # zero syncs inside the timed region.
    ledger_final_loss = None
    ledger_time_to_loss = None
    try:
        from mxnet_tpu.telemetry import ledger as _ledger
        if _ledger.enabled():
            # phase clocks are perf_counter (process uptime) — shift
            # them onto the epoch timeline so every scalars record's
            # 't' matches the rest of the JSONL (documented contract)
            epoch_anchor = time.time() - time.perf_counter()
            for phase_t0, phase_dt, losses, base in (
                    (warm_t0, warmup_dt, warm_losses, 0),
                    (t0, dt, bench_losses, WARMUP_STEPS)):
                n = len(losses)
                for i, l in enumerate(losses):
                    _ledger.feed((base + i + 1) * STEPS_PER_CALL,
                                 float(np.asarray(l)),
                                 t=epoch_anchor + phase_t0
                                 + (i + 1) * phase_dt / n)
            ledger_final_loss = _ledger.final_loss()
            tgt = _ledger.progress_target(0.9)
            secs = _ledger.time_to_loss(tgt)
            if tgt is not None and secs is not None:
                ledger_time_to_loss = {'target': round(tgt, 6),
                                       'seconds': secs}
    except Exception as e:  # noqa: BLE001 — the ledger must never cost
        _log('ledger feed failed (headline unaffected): %s' % e)
    del warm_losses, bench_losses

    # sentinel-overhead probe (MXTPU_BENCH_HEALTH=0 skips): the same
    # in-graph reductions MXTPU_HEALTH adds, timed against the base
    # step — keeps the <2% overhead contract measured across releases.
    # Runs AFTER the measurement, consuming the now-expendable buffers.
    health_probe = None
    if os.environ.get('MXTPU_BENCH_HEALTH', '1') != '0':
        health_probe = _measure_health_overhead(
            raw_step, masters, aux, vel, images, labels, key,
            dt / bench_steps)

    peak, kind = _peak_flops(devices[0])
    mfu = (flops_per_step * bench_steps / dt / peak) if peak else None
    if MODEL == 'transformer':
        tok_s = bench_steps * STEPS_PER_CALL * tokens_per_batch / dt
        _log('%.0f tokens/s over %d calls x %d steps (%.2fs); '
             'device=%s mfu=%s'
             % (tok_s, bench_steps, STEPS_PER_CALL, dt, kind,
                '%.1f%%' % (100 * mfu) if mfu is not None else 'n/a'))
        out = {
            'metric': 'transformer_train_throughput_bf16',
            'value': round(tok_s, 1),
            'unit': 'tokens/sec',
            'batch': int(images.shape[0]),
            'seq': int(images.shape[1]),
            'device': kind or platform,
            'platform': platform,
            'steps_per_call': STEPS_PER_CALL,
        }
        if mfu is not None:
            # the perf north star is 50% MFU; report progress against it
            out['vs_baseline'] = round(mfu / 0.5, 3)
    else:
        img_s = bench_steps * STEPS_PER_CALL * BATCH / dt
        _log('%.2f img/s over %d calls x %d steps (%.2fs); '
             'device=%s mfu=%s'
             % (img_s, bench_steps, STEPS_PER_CALL, dt, kind,
                '%.1f%%' % (100 * mfu) if mfu is not None else 'n/a'))
        out = {
            'metric': '%s_train_throughput_bf16' % MODEL,
            'value': round(img_s, 2),
            'unit': 'images/sec',
            'vs_baseline': round(img_s / BASELINE_IMG_S[MODEL], 3),
            'batch': BATCH,
            'device': kind or platform,
            'platform': platform,
            'steps_per_call': STEPS_PER_CALL,
        }
    if mfu is not None:
        out['mfu'] = round(mfu, 4)
    if ledger_final_loss is not None:
        # run-ledger metrics (ISSUE 15): tools/bench_diff.py gates
        # final_loss (a nan/diverged run must not bank as a healthy
        # throughput number); time_to_loss is ledger context, ungated.
        # bench_steps scales with measured throughput, so convergence
        # is only comparable between runs that trained the same number
        # of steps — final_loss_step lets bench_diff skip the gate
        # (visibly) on a mismatch instead of conflating a throughput
        # change with a convergence change
        out['final_loss'] = round(float(ledger_final_loss), 6)
        out['final_loss_step'] = \
            (WARMUP_STEPS + bench_steps) * STEPS_PER_CALL
    if ledger_time_to_loss is not None:
        out['time_to_loss'] = ledger_time_to_loss
    if BACKEND_ATTEMPTS:
        # how many probe rounds the backend cost this run (1 = first
        # try; >1 = the flaky-tunnel shape; CPU fallback burned all)
        out['backend_attempts'] = BACKEND_ATTEMPTS
    if health_probe:
        out['health'] = health_probe
    if temp_bytes:
        out['xla_temp_bytes'] = temp_bytes
    if step_analysis.get('live_bytes'):
        # steady-state per-dispatch footprint (args + temp + outputs
        # minus donated-alias bytes): the donation ledger's gated metric
        out['xla_live_bytes'] = step_analysis['live_bytes']
    if MIRROR:
        out['backward_mirror'] = MIRROR
    if compile_warm_s is not None:
        # cpu form: measured by compile-only probe children against a
        # discarded scratch dir; the measuring process itself ran with
        # the cache off (no 'dir' — there is nothing durable to point
        # at), so the pair quantifies what MXTPU_COMPILE_CACHE would
        # refund on a warm start
        out['compile_cache'] = {'cold_s': round(cache_cold_s, 2),
                                'warm_s': round(compile_warm_s, 2),
                                'probe': 'compile-only subprocesses, '
                                         'scratch cache discarded'}
    elif served_from_cache is not None:
        # device form: one number per run; 'served_from_cache' says
        # whether THIS run was the warm one (pair up across runs)
        out['compile_cache'] = {'dir': cache_dir,
                                'compile_s': round(compile_cold_s, 2),
                                'served_from_cache': served_from_cache}
    if platform.startswith('cpu'):
        out['note'] = ('cpu run at reduced batch; not config-comparable '
                       'to the batch-32 GPU baseline')
    tel = _telemetry_breakdown(
        devices[0], step_ms=dt / (bench_steps * STEPS_PER_CALL) * 1e3)
    if tel:
        out['telemetry'] = tel
        # top-level copy of the gated metric (tools/bench_diff.py gates
        # goodput_pct: lower = regression) + the per-bucket breakdown
        # the diff renders next to it
        good = tel.get('goodput') or {}
        if good.get('goodput_pct') is not None:
            out['goodput_pct'] = good['goodput_pct']
            out['goodput'] = {'buckets': good.get('buckets'),
                              'badput_top': good.get('badput_top'),
                              'wall_s': good.get('wall_s')}
        # top-level copy of the headroom gate (bench_diff gates
        # mem_headroom_pct: lower = regression) — a program that grew
        # its footprint shows up as a shrunken safety margin here
        mem = tel.get('memory') or {}
        if mem.get('headroom_pct') is not None:
            out['mem_headroom_pct'] = mem['headroom_pct']
        # top-level copy of the wire-byte gate (bench_diff gates
        # bytes_on_wire_per_step: higher = regression)
        if tel.get('bytes_on_wire_per_step') is not None:
            out['bytes_on_wire_per_step'] = \
                tel['bytes_on_wire_per_step']
            if tel.get('compression_ratio') is not None:
                out['compression_ratio'] = tel['compression_ratio']
        # top-level copy of the step-phase gate (bench_diff gates
        # host_overhead_pct: higher = regression) — host-side work
        # creeping into the step shows up as a grown share here
        pb = tel.get('step_phase_breakdown') or {}
        if pb.get('host_pct') is not None:
            out['step_phase_breakdown'] = pb
            out['host_overhead_pct'] = pb['host_pct']
    # sharded-vs-replicated weight-update A/B (MXTPU_SHARDED_UPDATE):
    # only runs at dp > 1, and AFTER the telemetry fold above so the
    # probe model's compiles/programs/roofline never contaminate the
    # headline's telemetry block (the infer probe follows the same
    # rule); a failure must never cost the headline number
    sharded_ab = None
    if os.environ.get('MXTPU_BENCH_SHARDED_AB', '1') != '0':
        try:
            sharded_ab = run_sharded_update_ab(platform)
        except Exception as e:  # noqa: BLE001
            _log('sharded-update A/B failed (headline unaffected): %s' % e)
    # donation + BN-one-pass A/B (ISSUE 12): real Module.fit fused
    # window, pre-PR program vs shipped defaults — temp/live bytes,
    # overlap evidence, throughput. Runs after the telemetry fold for
    # the same contamination rule; banks gracefully on CPU fallback.
    fused_ab = None
    if os.environ.get('MXTPU_BENCH_FUSED_AB', '1') != '0':
        try:
            fused_ab = run_fused_window_ab(platform)
        except Exception as e:  # noqa: BLE001
            _log('fused-window A/B failed (headline unaffected): %s' % e)
    if fused_ab:
        out['fused_window_ab'] = fused_ab
        if fused_ab['tuned'].get('overlap_ms_p50') is not None:
            # update/upload overlap per window, the ledger's evidence
            # that the optimizer host tail hides under the transfer
            out['overlap_ms'] = fused_ab['tuned']['overlap_ms_p50']
    # serving bench (ISSUE 13): closed-loop load against the in-process
    # continuous-batching plane; same contamination/failure rules as
    # the A/Bs above — the headline number is never at risk
    serving = None
    if os.environ.get('MXTPU_BENCH_SERVING', '1') != '0':
        try:
            serving = run_serving_bench(platform)
        except Exception as e:  # noqa: BLE001
            _log('serving bench failed (headline unaffected): %s' % e)
    if serving:
        out['serving_bench'] = serving
        # top-level copies of the gated/ledger metrics
        # (tools/bench_diff.py gates serving_p99_ms AND
        # serving_queue_wait_p50_ms at 10%)
        for k in ('serving_p50_ms', 'serving_p99_ms',
                  'serving_throughput_rps', 'pad_fraction',
                  'serving_queue_wait_p50_ms', 'serving_stage_p50_ms'):
            if serving.get(k) is not None:
                out[k] = serving[k]
    if sharded_ab:
        out['sharded_update_ab'] = sharded_ab
        # top-level copies of the gated/ledger metrics: per-device
        # optimizer-state bytes with the sharded update ON (the
        # tools/bench_diff.py gate reads this) and the update
        # collectives' per-step traffic
        if sharded_ab.get('opt_state_bytes_per_device') is not None:
            out['opt_state_bytes_per_device'] = \
                sharded_ab['opt_state_bytes_per_device']
        if sharded_ab.get('update_comm_bytes') is not None:
            out['update_comm_bytes'] = sharded_ab['update_comm_bytes']
    # inference tier (ISSUE 2): fused Module.predict vs the per-batch
    # path, printed BEFORE the training line — the LAST line stays the
    # authoritative training number, and a failure here can never lose
    # it
    if MODEL == 'resnet50':
        try:
            kind_ = kind or platform
            print(json.dumps(run_infer_bench(platform, kind_)), flush=True)
        except Exception as e:  # noqa: BLE001
            _log('infer bench failed (training number unaffected): %s' % e)
    # emit the measured number NOW so an interrupted reprobe window can
    # never lose it; if a real device recovers below, its JSON is
    # printed after — the LAST line is authoritative
    print(json.dumps(out), flush=True)
    if platform == 'cpu(fallback)':
        # fallback only (a genuinely CPU-only host never reprobes):
        # a wedged tunnel can recover, so keep trying within the budget
        _MIN_LATE_BENCH_S = 180.0
        while True:
            elapsed = time.perf_counter() - _START
            if elapsed > BUDGET_S - (REPROBE_TIMEOUT_S + _MIN_LATE_BENCH_S):
                _log('budget exhausted; the banked CPU number stands')
                break
            _log('reprobing device backend (%.0fs into %.0fs budget)'
                 % (elapsed, BUDGET_S))
            status = _probe_subprocess(REPROBE_TIMEOUT_S)
            if status.startswith('ok') and 'cpu' not in status:
                remaining = BUDGET_S - (time.perf_counter() - _START)
                if remaining < _MIN_LATE_BENCH_S:
                    break
                late = _late_tpu_attempt(remaining)
                if late is not None:
                    print(json.dumps(late), flush=True)
                break
            time.sleep(REPROBE_SLEEP_S)


if __name__ == '__main__':
    main()
