/*
 * c_api.h — C ABI of the TPU-native framework (N13).
 *
 * Reference: include/mxnet/c_api.h (146 MXNET_DLL functions). Same
 * contract: opaque handles, int return codes (0 ok / -1 error with the
 * message via MXGetLastError, thread-local), caller-visible strings and
 * shape buffers owned by the library in thread-local storage, valid
 * until the next call on the same thread.
 *
 * TPU-native design: the reference's C API fronts its C++ core; this
 * framework's core is the XLA runtime hosted by CPython, so the library
 * embeds the interpreter (initialized lazily on first call) and each
 * entry point delegates to mxnet_tpu._c_api_impl. The data plane is
 * unchanged — XLA executables on device — the C frontier carries
 * control and host buffers only, exactly like the reference's.
 */
#ifndef MXNET_TPU_C_API_H_
#define MXNET_TPU_C_API_H_

#ifdef __cplusplus
extern "C" {
#endif

#include <stdint.h>
#include <stddef.h>
#include <stdbool.h>

typedef uint32_t mx_uint;
typedef float mx_float;

typedef void *NDArrayHandle;
typedef const void *FunctionHandle;
typedef void *AtomicSymbolCreator;
typedef void *SymbolHandle;
typedef void *ExecutorHandle;
typedef void *DataIterHandle;
typedef void *KVStoreHandle;
typedef void *RecordIOHandle;
typedef void *CachedOpHandle;
typedef void *RtcHandle;

/* -- C callback protocol (reference c_api.h:122-177) -- */
typedef int (*MXGenericCallback)(void);

struct MXCallbackList {
  int num_callbacks;
  int (**callbacks)(void);
  void **contexts;
};

enum CustomOpCallbacks {
  kCustomOpDelete,
  kCustomOpForward,
  kCustomOpBackward
};

enum CustomOpPropCallbacks {
  kCustomOpPropDelete,
  kCustomOpPropListArguments,
  kCustomOpPropListOutputs,
  kCustomOpPropListAuxiliaryStates,
  kCustomOpPropInferShape,
  kCustomOpPropDeclareBackwardDependency,
  kCustomOpPropCreateOperator,
  kCustomOpPropInferType
};

typedef int (*CustomOpFBFunc)(int size, void **ptrs, int *tags,
                              const int *reqs, const int is_train,
                              void *state);
typedef int (*CustomOpDelFunc)(void *state);
typedef int (*CustomOpListFunc)(char ***args, void *state);
typedef int (*CustomOpInferShapeFunc)(int num_input, int *ndims,
                                      unsigned **shapes, void *state);
typedef int (*CustomOpInferTypeFunc)(int num_input, int *types, void *state);
typedef int (*CustomOpBwdDepFunc)(const int *out_grad, const int *in_data,
                                  const int *out_data, int *num_deps,
                                  int **rdeps, void *state);
typedef int (*CustomOpCreateFunc)(const char *ctx, int num_inputs,
                                  unsigned **shapes, const int *ndims,
                                  const int *dtypes,
                                  struct MXCallbackList *ret, void *state);
typedef int (*CustomOpPropCreator)(const char *op_type, const int num_kwargs,
                                   const char **keys, const char **values,
                                   struct MXCallbackList *ret);

enum CustomFunctionCallbacks {
  kCustomFunctionBackward,
  kCustomFunctionDelete
};

typedef int (*CustomFunctionBwdFunc)(int num_ograds, int num_igrads,
                                     void **ptrs, const int *reqs,
                                     const int is_train, void *state);
typedef int (*CustomFunctionDelFunc)(void *state);

typedef void (*ExecutorMonitorCallback)(const char *name, NDArrayHandle arr,
                                        void *callback_handle);
typedef void (*MXKVStoreUpdater)(int key, NDArrayHandle recv,
                                 NDArrayHandle local, void *handle);
typedef void (*MXKVStoreStrUpdater)(const char *key, NDArrayHandle recv,
                                    NDArrayHandle local, void *handle);

/*! Return the last error message on this thread (empty string if none). */
const char *MXGetLastError();

/* ------------------------------------------------------------- misc -- */
int MXGetVersion(int *out);
int MXRandomSeed(int seed);
int MXNotifyShutdown();
int MXSetNumOMPThreads(int thread_num);
int MXSetProfilerConfig(int mode, const char *filename);
int MXSetProfilerState(int state);
int MXDumpProfile();

/* ---------------------------------------------------------- ndarray -- */
int MXNDArrayCreateNone(NDArrayHandle *out);
int MXNDArrayCreate(const mx_uint *shape, mx_uint ndim, int dev_type,
                    int dev_id, int delay_alloc, NDArrayHandle *out);
int MXNDArrayCreateEx(const mx_uint *shape, mx_uint ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle *out);
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void *data,
                             size_t size);
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data, size_t size);
int MXNDArrayWaitToRead(NDArrayHandle handle);
int MXNDArrayWaitToWrite(NDArrayHandle handle);
int MXNDArrayWaitAll();
int MXNDArrayFree(NDArrayHandle handle);
int MXNDArraySlice(NDArrayHandle handle, mx_uint begin, mx_uint end,
                   NDArrayHandle *out);
int MXNDArrayAt(NDArrayHandle handle, mx_uint idx, NDArrayHandle *out);
int MXNDArrayReshape(NDArrayHandle handle, int ndim, const int *dims,
                     NDArrayHandle *out);
int MXNDArrayGetShape(NDArrayHandle handle, mx_uint *out_dim,
                      const mx_uint **out_pdata);
int MXNDArrayGetDType(NDArrayHandle handle, int *out);
int MXNDArrayGetStorageType(NDArrayHandle handle, int *out);
int MXNDArrayGetContext(NDArrayHandle handle, int *out_dev_type,
                        int *out_dev_id);
/*! Host mirror of the device buffer (fp32 for bf16 arrays); valid until
 *  MXNDArrayFree(handle). */
int MXNDArrayGetData(NDArrayHandle handle, void **out_pdata);
int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle *out);
int MXNDArrayDetach(NDArrayHandle handle, NDArrayHandle *out);
int MXNDArraySave(const char *fname, mx_uint num_args, NDArrayHandle *args,
                  const char **keys);
int MXNDArrayLoad(const char *fname, mx_uint *out_size,
                  NDArrayHandle **out_arr, mx_uint *out_name_size,
                  const char ***out_names);
int MXNDArraySaveRawBytes(NDArrayHandle handle, size_t *out_size,
                          const char **out_buf);
int MXNDArrayLoadFromRawBytes(const void *buf, size_t size,
                              NDArrayHandle *out);
int MXNDArrayCreateSparseEx(int storage_type, const mx_uint *shape,
                            mx_uint ndim, int dev_type, int dev_id,
                            int delay_alloc, int dtype, mx_uint num_aux,
                            int *aux_type, mx_uint *aux_ndims,
                            const mx_uint *aux_shape, NDArrayHandle *out);
int MXNDArrayGetAuxType(NDArrayHandle handle, mx_uint i, int *out_type);
int MXNDArrayGetAuxNDArray(NDArrayHandle handle, mx_uint i,
                           NDArrayHandle *out);
int MXNDArrayGetDataNDArray(NDArrayHandle handle, NDArrayHandle *out);
int MXNDArrayGetGradState(NDArrayHandle handle, int *out);
int MXNDArraySetGradState(NDArrayHandle handle, int state);
/*! Copy src (or its aux array i; i < 0 means the data array) into dst. */
int MXNDArraySyncCopyFromNDArray(NDArrayHandle handle_dst,
                                 const NDArrayHandle handle_src, const int i);

/* -------------------------------------------------------- operators -- */
int MXListAllOpNames(mx_uint *out_size, const char ***out_array);
/*! Invoke an operator imperatively. If *num_outputs > 0, *outputs holds
 *  caller-provided output handles; otherwise the library allocates them. */
int MXImperativeInvoke(AtomicSymbolCreator creator, int num_inputs,
                       NDArrayHandle *inputs, int *num_outputs,
                       NDArrayHandle **outputs, int num_params,
                       const char **param_keys, const char **param_vals);
int MXImperativeInvokeEx(AtomicSymbolCreator creator, int num_inputs,
                         NDArrayHandle *inputs, int *num_outputs,
                         NDArrayHandle **outputs, int num_params,
                         const char **param_keys, const char **param_vals,
                         const int **out_stypes);
int MXCustomOpRegister(const char *op_type, CustomOpPropCreator creator);

/* -- legacy NDArray-function registry (reference c_api.h:407-500) -- */
int MXListFunctions(mx_uint *out_size, FunctionHandle **out_array);
int MXGetFunction(const char *name, FunctionHandle *out);
int MXFuncGetInfo(FunctionHandle fun, const char **name,
                  const char **description, mx_uint *num_args,
                  const char ***arg_names, const char ***arg_type_infos,
                  const char ***arg_descriptions, const char **return_type);
int MXFuncDescribe(FunctionHandle fun, mx_uint *num_use_vars,
                   mx_uint *num_scalars, mx_uint *num_mutate_vars,
                   int *type_mask);
int MXFuncInvoke(FunctionHandle fun, NDArrayHandle *use_vars,
                 mx_float *scalar_args, NDArrayHandle *mutate_vars);
int MXFuncInvokeEx(FunctionHandle fun, NDArrayHandle *use_vars,
                   mx_float *scalar_args, NDArrayHandle *mutate_vars,
                   int num_params, char **param_keys, char **param_vals);

/* --------------------------------------------------------- autograd -- */
int MXAutogradSetIsRecording(int is_recording, int *prev);
int MXAutogradSetIsTraining(int is_training, int *prev);
int MXAutogradIsRecording(bool *curr);
int MXAutogradIsTraining(bool *curr);
int MXAutogradMarkVariables(mx_uint num_var, NDArrayHandle *var_handles,
                            mx_uint *reqs_array,
                            NDArrayHandle *grad_handles);
int MXAutogradBackward(mx_uint num_output, NDArrayHandle *output_handles,
                       NDArrayHandle *ograd_handles, int retain_graph);
int MXAutogradBackwardEx(mx_uint num_output, NDArrayHandle *output_handles,
                         NDArrayHandle *ograd_handles, int retain_graph,
                         int train_mode);
int MXAutogradComputeGradient(mx_uint num_output,
                              NDArrayHandle *output_handles);
/*! Export the recorded imperative history of `handle` as a Symbol. */
int MXAutogradGetSymbol(NDArrayHandle handle, SymbolHandle *out);
int MXCustomFunctionRecord(int num_inputs, NDArrayHandle *inputs,
                           int num_outputs, NDArrayHandle *outputs,
                           struct MXCallbackList *callbacks);

/* --------------------------------------------------------- cachedop -- */
int MXCreateCachedOp(SymbolHandle handle, CachedOpHandle *out);
int MXFreeCachedOp(CachedOpHandle handle);
int MXInvokeCachedOp(CachedOpHandle handle, int num_inputs,
                     NDArrayHandle *inputs, int *num_outputs,
                     NDArrayHandle **outputs);
int MXInvokeCachedOpEx(CachedOpHandle handle, int num_inputs,
                       NDArrayHandle *inputs, int *num_outputs,
                       NDArrayHandle **outputs, const int **out_stypes);

/* ----------------------------------------------------------- symbol -- */
int MXSymbolListAtomicSymbolCreators(mx_uint *out_size,
                                     AtomicSymbolCreator **out_array);
int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator creator,
                                const char **name);
int MXSymbolGetAtomicSymbolInfo(AtomicSymbolCreator creator,
                                const char **name, const char **description,
                                mx_uint *num_args,
                                const char ***arg_names,
                                const char ***arg_type_infos,
                                const char ***arg_descriptions,
                                const char **key_var_num_args,
                                const char **return_type);
int MXSymbolCreateAtomicSymbol(AtomicSymbolCreator creator,
                               mx_uint num_param, const char **keys,
                               const char **vals, SymbolHandle *out);
int MXSymbolCreateVariable(const char *name, SymbolHandle *out);
int MXSymbolCreateGroup(mx_uint num_symbols, SymbolHandle *symbols,
                        SymbolHandle *out);
int MXSymbolCreateFromFile(const char *fname, SymbolHandle *out);
int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out);
int MXSymbolSaveToFile(SymbolHandle symbol, const char *fname);
int MXSymbolSaveToJSON(SymbolHandle symbol, const char **out_json);
int MXSymbolFree(SymbolHandle symbol);
int MXSymbolCopy(SymbolHandle symbol, SymbolHandle *out);
int MXSymbolPrint(SymbolHandle symbol, const char **out_str);
int MXSymbolGetName(SymbolHandle symbol, const char **out, int *success);
int MXSymbolGetAttr(SymbolHandle symbol, const char *key, const char **out,
                    int *success);
int MXSymbolSetAttr(SymbolHandle symbol, const char *key, const char *value);
int MXSymbolListAttr(SymbolHandle symbol, mx_uint *out_size,
                     const char ***out);
int MXSymbolListAttrShallow(SymbolHandle symbol, mx_uint *out_size,
                            const char ***out);
int MXSymbolListArguments(SymbolHandle symbol, mx_uint *out_size,
                          const char ***out_str_array);
int MXSymbolListOutputs(SymbolHandle symbol, mx_uint *out_size,
                        const char ***out_str_array);
int MXSymbolListAuxiliaryStates(SymbolHandle symbol, mx_uint *out_size,
                                const char ***out_str_array);
int MXSymbolGetInternals(SymbolHandle symbol, SymbolHandle *out);
int MXSymbolGetChildren(SymbolHandle symbol, SymbolHandle *out);
int MXSymbolGetOutput(SymbolHandle symbol, mx_uint index, SymbolHandle *out);
int MXSymbolCompose(SymbolHandle sym, const char *name, mx_uint num_args,
                    const char **keys, SymbolHandle *args);
int MXSymbolGrad(SymbolHandle sym, mx_uint num_wrt, const char **wrt,
                 SymbolHandle *out);
int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args, const char **keys,
                       const mx_uint *arg_ind_ptr, const mx_uint *arg_shape_data,
                       mx_uint *in_shape_size, const mx_uint **in_shape_ndim,
                       const mx_uint ***in_shape_data,
                       mx_uint *out_shape_size, const mx_uint **out_shape_ndim,
                       const mx_uint ***out_shape_data,
                       mx_uint *aux_shape_size, const mx_uint **aux_shape_ndim,
                       const mx_uint ***aux_shape_data, int *complete);
int MXSymbolInferShapePartial(SymbolHandle sym, mx_uint num_args,
                              const char **keys, const mx_uint *arg_ind_ptr,
                              const mx_uint *arg_shape_data,
                              mx_uint *in_shape_size,
                              const mx_uint **in_shape_ndim,
                              const mx_uint ***in_shape_data,
                              mx_uint *out_shape_size,
                              const mx_uint **out_shape_ndim,
                              const mx_uint ***out_shape_data,
                              mx_uint *aux_shape_size,
                              const mx_uint **aux_shape_ndim,
                              const mx_uint ***aux_shape_data, int *complete);
int MXSymbolInferType(SymbolHandle sym, mx_uint num_args, const char **keys,
                      const int *arg_type_data, mx_uint *in_type_size,
                      const int **in_type_data, mx_uint *out_type_size,
                      const int **out_type_data, mx_uint *aux_type_size,
                      const int **aux_type_data, int *complete);

/* --------------------------------------------------------- executor -- */
int MXExecutorFree(ExecutorHandle handle);
int MXExecutorPrint(ExecutorHandle handle, const char **out_str);
int MXExecutorForward(ExecutorHandle handle, int is_train);
int MXExecutorBackward(ExecutorHandle handle, mx_uint len,
                       NDArrayHandle *head_grads);
int MXExecutorOutputs(ExecutorHandle handle, mx_uint *out_size,
                      NDArrayHandle **out);
int MXExecutorBind(SymbolHandle symbol_handle, int dev_type, int dev_id,
                   mx_uint len, NDArrayHandle *in_args,
                   NDArrayHandle *arg_grad_store, mx_uint *grad_req_type,
                   mx_uint aux_states_len, NDArrayHandle *aux_states,
                   ExecutorHandle *out);
int MXExecutorBackwardEx(ExecutorHandle handle, mx_uint len,
                         NDArrayHandle *head_grads, int is_train);
/*! Bind with per-group device placement (group2ctx). */
int MXExecutorBindX(SymbolHandle symbol_handle, int dev_type, int dev_id,
                    mx_uint num_map_keys, const char **map_keys,
                    const int *map_dev_types, const int *map_dev_ids,
                    mx_uint len, NDArrayHandle *in_args,
                    NDArrayHandle *arg_grad_store, mx_uint *grad_req_type,
                    mx_uint aux_states_len, NDArrayHandle *aux_states,
                    ExecutorHandle *out);
int MXExecutorBindEX(SymbolHandle symbol_handle, int dev_type, int dev_id,
                     mx_uint num_map_keys, const char **map_keys,
                     const int *map_dev_types, const int *map_dev_ids,
                     mx_uint len, NDArrayHandle *in_args,
                     NDArrayHandle *arg_grad_store, mx_uint *grad_req_type,
                     mx_uint aux_states_len, NDArrayHandle *aux_states,
                     ExecutorHandle shared_exec, ExecutorHandle *out);
/*! simple_bind: the library allocates arg/grad/aux arrays from shape,
 *  dtype and stype hints (reference c_api_executor.cc:167). */
int MXExecutorSimpleBind(
    SymbolHandle symbol_handle, int dev_type, int dev_id,
    const mx_uint num_g2c_keys, const char **g2c_keys,
    const int *g2c_dev_types, const int *g2c_dev_ids,
    const mx_uint provided_grad_req_list_len,
    const char **provided_grad_req_names,
    const char **provided_grad_req_types,
    const mx_uint num_provided_arg_shapes,
    const char **provided_arg_shape_names,
    const mx_uint *provided_arg_shape_data,
    const mx_uint *provided_arg_shape_idx,
    const mx_uint num_provided_arg_dtypes,
    const char **provided_arg_dtype_names, const int *provided_arg_dtypes,
    const mx_uint num_provided_arg_stypes,
    const char **provided_arg_stype_names, const int *provided_arg_stypes,
    const mx_uint num_shared_arg_names, const char **shared_arg_name_list,
    int *shared_buffer_len, const char **shared_buffer_name_list,
    NDArrayHandle *shared_buffer_handle_list,
    const char ***updated_shared_buffer_name_list,
    NDArrayHandle **updated_shared_buffer_handle_list,
    mx_uint *num_in_args, NDArrayHandle **in_args, NDArrayHandle **arg_grads,
    mx_uint *num_aux_states, NDArrayHandle **aux_states,
    ExecutorHandle shared_exec_handle, ExecutorHandle *out);
int MXExecutorSetMonitorCallback(ExecutorHandle handle,
                                 ExecutorMonitorCallback callback,
                                 void *callback_handle);

/* ---------------------------------------------------------- data io -- */
int MXListDataIters(mx_uint *out_size, DataIterHandle **out_array);
int MXDataIterGetIterInfo(DataIterHandle creator, const char **name,
                          const char **description, mx_uint *num_args,
                          const char ***arg_names,
                          const char ***arg_type_infos,
                          const char ***arg_descriptions);
int MXDataIterCreateIter(DataIterHandle creator, mx_uint num_param,
                         const char **keys, const char **vals,
                         DataIterHandle *out);
int MXDataIterFree(DataIterHandle handle);
int MXDataIterNext(DataIterHandle handle, int *out);
int MXDataIterBeforeFirst(DataIterHandle handle);
int MXDataIterGetData(DataIterHandle handle, NDArrayHandle *out);
int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle *out);
int MXDataIterGetPadNum(DataIterHandle handle, int *pad);
int MXDataIterGetIndex(DataIterHandle handle, uint64_t **out_index,
                       uint64_t *out_size);

/* ---------------------------------------------------------- kvstore -- */
int MXKVStoreCreate(const char *type, KVStoreHandle *out);
int MXKVStoreFree(KVStoreHandle handle);
int MXKVStoreInit(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals);
int MXKVStorePush(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals, int priority);
int MXKVStorePull(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals, int priority);
int MXKVStoreGetType(KVStoreHandle handle, const char **type);
int MXKVStoreGetRank(KVStoreHandle handle, int *ret);
int MXKVStoreGetGroupSize(KVStoreHandle handle, int *ret);
int MXKVStoreIsWorkerNode(int *ret);
int MXKVStoreIsServerNode(int *ret);
int MXKVStoreIsSchedulerNode(int *ret);
int MXKVStoreBarrier(KVStoreHandle handle);
int MXKVStoreGetNumDeadNode(KVStoreHandle handle, int node_id,
                            int *number);
int MXKVStoreRunServer(KVStoreHandle handle);
int MXKVStoreSendCommmandToServers(KVStoreHandle handle, int cmd_id,
                                   const char *cmd_body);
int MXInitPSEnv(mx_uint num_vars, const char **keys, const char **vals);
int MXKVStoreInitEx(KVStoreHandle handle, mx_uint num, const char **keys,
                    NDArrayHandle *vals);
int MXKVStorePushEx(KVStoreHandle handle, mx_uint num, const char **keys,
                    NDArrayHandle *vals, int priority);
int MXKVStorePullEx(KVStoreHandle handle, mx_uint num, const char **keys,
                    NDArrayHandle *vals, int priority);
int MXKVStorePullRowSparse(KVStoreHandle handle, mx_uint num,
                           const int *keys, NDArrayHandle *vals,
                           const NDArrayHandle *row_ids, int priority);
int MXKVStorePullRowSparseEx(KVStoreHandle handle, mx_uint num,
                             const char **keys, NDArrayHandle *vals,
                             const NDArrayHandle *row_ids, int priority);
int MXKVStoreSetBarrierBeforeExit(KVStoreHandle handle,
                                  const int barrier_before_exit);
int MXKVStoreSetUpdater(KVStoreHandle handle, MXKVStoreUpdater updater,
                        void *updater_handle);
int MXKVStoreSetUpdaterEx(KVStoreHandle handle, MXKVStoreUpdater updater,
                          MXKVStoreStrUpdater str_updater,
                          void *updater_handle);

/* --------------------------------------------------------- recordio -- */
/* Native framed stream (src/recordio.cc) — no interpreter involved. */
int MXRecordIOWriterCreate(const char *uri, RecordIOHandle *out);
int MXRecordIOWriterFree(RecordIOHandle handle);
int MXRecordIOWriterWriteRecord(RecordIOHandle handle, const char *buf,
                                size_t size);
int MXRecordIOWriterTell(RecordIOHandle handle, size_t *pos);
int MXRecordIOReaderCreate(const char *uri, RecordIOHandle *out);
int MXRecordIOReaderFree(RecordIOHandle handle);
/* *size set to (size_t)-1 at end of stream. */
int MXRecordIOReaderReadRecord(RecordIOHandle handle, const char **buf,
                               size_t *size);
int MXRecordIOReaderSeek(RecordIOHandle handle, size_t pos);

/* -------------------------------------------------------------- rtc -- */
/* Runtime kernel compilation (reference c_api.h:1666: CUDA C there;
 * jnp/pallas python source here — mx.rtc semantics). Grid/block dims in
 * MXRtcPush are accepted for signature parity and ignored (XLA owns
 * scheduling). */
int MXRtcCreate(char *name, mx_uint num_input, mx_uint num_output,
                char **input_names, char **output_names,
                NDArrayHandle *inputs, NDArrayHandle *outputs, char *kernel,
                RtcHandle *out);
int MXRtcPush(RtcHandle handle, mx_uint num_input, mx_uint num_output,
              NDArrayHandle *inputs, NDArrayHandle *outputs,
              mx_uint gridDimX, mx_uint gridDimY, mx_uint gridDimZ,
              mx_uint blockDimX, mx_uint blockDimY, mx_uint blockDimZ);
int MXRtcFree(RtcHandle handle);

#ifdef __cplusplus
}
#endif

#endif /* MXNET_TPU_C_API_H_ */
