/*
 * c_predict_api.h — standalone inference ABI (N19).
 *
 * Reference: include/mxnet/c_predict_api.h (MXPredCreate family, 12
 * functions) — the "amalgamation" deployment surface: load a saved
 * symbol json + param blob, feed fp32 inputs, read fp32 outputs, no
 * Python at the call site. Same contract here; the interpreter is an
 * implementation detail embedded inside the library.
 */
#ifndef MXNET_TPU_C_PREDICT_API_H_
#define MXNET_TPU_C_PREDICT_API_H_

#ifdef __cplusplus
extern "C" {
#endif

#include <stdint.h>
#include <stddef.h>

typedef uint32_t mx_uint;
typedef float mx_float;
typedef void *PredictorHandle;
typedef void *NDListHandle;

const char *MXGetLastError();

/*!
 * Create a predictor from a symbol json string and a parameter blob
 * (the byte contents of a `.params` file saved by this framework or
 * written via MXNDArraySave).
 * input_keys/input_shape_indptr/input_shape_data describe the named
 * input shapes, CSR-style, as in the reference.
 */
int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char **input_keys,
                 const mx_uint *input_shape_indptr,
                 const mx_uint *input_shape_data, PredictorHandle *out);

/*! Same, keeping only the listed output heads. */
int MXPredCreatePartialOut(const char *symbol_json_str,
                           const void *param_bytes, int param_size,
                           int dev_type, int dev_id,
                           mx_uint num_input_nodes, const char **input_keys,
                           const mx_uint *input_shape_indptr,
                           const mx_uint *input_shape_data,
                           mx_uint num_output_nodes,
                           const char **output_keys, PredictorHandle *out);

int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint **shape_data, mx_uint *shape_ndim);
int MXPredSetInput(PredictorHandle handle, const char *key,
                   const mx_float *data, mx_uint size);
int MXPredForward(PredictorHandle handle);

/*!
 * Interactive stepping forward for progress display on slow models
 * (reference include/mxnet/c_predict_api.h:160-169): call from step=0
 * and keep incrementing until *step_left == 0, at which point the
 * outputs are complete. Each step executes exactly one operator node.
 */
int MXPredPartialForward(PredictorHandle handle, int step, int *step_left);
int MXPredGetOutput(PredictorHandle handle, mx_uint index, mx_float *data,
                    mx_uint size);
int MXPredFree(PredictorHandle handle);

/*! Load an NDArray-save blob as a list of named fp32 arrays. */
int MXNDListCreate(const char *nd_file_bytes, int nd_file_size,
                   NDListHandle *out, mx_uint *out_length);
int MXNDListGet(NDListHandle handle, mx_uint index, const char **out_key,
                const mx_float **out_data, const mx_uint **out_shape,
                mx_uint *out_ndim);
int MXNDListFree(NDListHandle handle);

#ifdef __cplusplus
}
#endif

#endif /* MXNET_TPU_C_PREDICT_API_H_ */
