/*
 * profiler.cc — chrome trace-event profiler.
 *
 * TPU-native rebuild of src/engine/profiler.{h,cc}: the reference
 * records OprExecStat (name, start/end µs, thread, device) inside
 * ThreadedEngine::ExecuteOprBlock and dumps chrome://tracing JSON
 * (profiler.h:106-127 DumpProfile/EmitEvent). Here the engine records
 * host-op spans the same way; device-side tracing belongs to the JAX/XLA
 * profiler, and the python layer (mxnet_tpu/profiler.py) merges both
 * streams into one trace file.
 */
#include <atomic>
#include <chrono>
#include <cstdio>
#include <mutex>
#include <stdexcept>
#include <string>
#include <vector>

#include "mxtpu.h"

namespace mxtpu {

int64_t NowUS() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

namespace profiler {

struct Event {
  std::string name;
  std::string category;
  int64_t start_us;
  int64_t end_us;
  int thread_id;
};

class Profiler {
 public:
  static Profiler *Get() {
    static Profiler inst;
    return &inst;
  }

  void SetState(bool running) { running_.store(running); }
  bool Running() const { return running_.load(std::memory_order_relaxed); }

  void Add(Event e) {
    std::lock_guard<std::mutex> lk(mu_);
    events_.push_back(std::move(e));
  }

  static std::string JsonEscape(const std::string &s) {
    std::string out;
    out.reserve(s.size());
    for (char c : s) {
      switch (c) {
        case '"': out += "\\\""; break;
        case '\\': out += "\\\\"; break;
        case '\n': out += "\\n"; break;
        case '\t': out += "\\t"; break;
        case '\r': out += "\\r"; break;
        default:
          if (static_cast<unsigned char>(c) < 0x20) {
            char buf[8];
            std::snprintf(buf, sizeof(buf), "\\u%04x", c);
            out += buf;
          } else {
            out += c;
          }
      }
    }
    return out;
  }

  void Dump(const char *path) {
    std::vector<Event> events;
    {
      std::lock_guard<std::mutex> lk(mu_);
      events.swap(events_);
    }
    FILE *fp = std::fopen(path, "w");
    if (!fp) throw std::runtime_error(std::string("cannot open ") + path);
    std::fprintf(fp, "{\n\"traceEvents\": [\n");
    bool first = true;
    for (const auto &e : events) {
      if (!first) std::fprintf(fp, ",\n");
      first = false;
      std::fprintf(fp,
                   "{\"name\":\"%s\",\"cat\":\"%s\",\"ph\":\"X\","
                   "\"ts\":%lld,\"dur\":%lld,\"pid\":0,\"tid\":%d}",
                   JsonEscape(e.name).c_str(),
                   JsonEscape(e.category).c_str(),
                   static_cast<long long>(e.start_us),
                   static_cast<long long>(e.end_us - e.start_us),
                   e.thread_id);
    }
    std::fprintf(fp, "\n],\n\"displayTimeUnit\": \"ms\"\n}\n");
    std::fclose(fp);
  }

 private:
  std::atomic<bool> running_{false};
  std::mutex mu_;
  std::vector<Event> events_;
};

}  // namespace profiler

bool ProfilerRunning() { return profiler::Profiler::Get()->Running(); }

void ProfilerRecordOpr(const std::string &name, int64_t start_us,
                       int64_t end_us, int thread_id) {
  profiler::Profiler::Get()->Add(
      {name.empty() ? "op" : name, "operator", start_us, end_us, thread_id});
}

}  // namespace mxtpu

void MXTSetLastError(const char *msg);

#define API_BEGIN() try {
#define API_END()                  \
  }                                \
  catch (const std::exception &e) { \
    MXTSetLastError(e.what());     \
    return -1;                     \
  }                                \
  return 0;

extern "C" int MXTProfilerSetState(int running) {
  API_BEGIN();
  mxtpu::profiler::Profiler::Get()->SetState(running != 0);
  API_END();
}

extern "C" int MXTProfilerAddEvent(const char *name, const char *category,
                                   int64_t start_us, int64_t end_us) {
  API_BEGIN();
  mxtpu::profiler::Profiler::Get()->Add(
      {name ? name : "event", category ? category : "misc", start_us, end_us,
       0});
  API_END();
}

extern "C" int MXTProfilerDump(const char *path) {
  API_BEGIN();
  mxtpu::profiler::Profiler::Get()->Dump(path);
  API_END();
}

extern "C" int64_t MXTNowUS() { return mxtpu::NowUS(); }
