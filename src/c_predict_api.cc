/*
 * c_predict_api.cc — standalone inference ABI (N19).
 *
 * Reference: src/c_api/c_predict_api.cc (predictor = symbol json +
 * param blob → bound executor; fp32 in/out). Delegates to the
 * _Predictor class in mxnet_tpu._c_api_impl through the same embedded
 * interpreter as c_api.cc.
 */
#include <Python.h>

#include <cstring>
#include <string>
#include <vector>

#include "../include/mxnet_tpu/c_predict_api.h"

/* shared with c_api.cc */
extern "C" const char *MXGetLastError();

namespace mxtpu_capi {
/* defined in c_api.cc */
bool EnsureBridge();
PyObject *Bridge();
int FailFromPython();
void SetError(const std::string &msg);
}  // namespace mxtpu_capi

namespace {

using mxtpu_capi::Bridge;
using mxtpu_capi::EnsureBridge;
using mxtpu_capi::FailFromPython;

#ifndef MXTPU_GIL_DEFINED
#define MXTPU_GIL_DEFINED
struct Gil {
  PyGILState_STATE state;
  Gil() { state = PyGILState_Ensure(); }
  ~Gil() { PyGILState_Release(state); }
};
#endif

thread_local std::vector<mx_uint> pred_shape;

struct NDList {
  PyObject *keys;    /* list[str] */
  PyObject *arrays;  /* list[NDArray] */
  /* per-entry materialized returns for MXNDListGet */
  std::string cur_key;
  std::string cur_data;
  std::vector<mx_uint> cur_shape;
};

#define PRED_BEGIN() \
  if (!EnsureBridge()) return -1; \
  Gil gil_;
#define CHECK_PYP(r) if ((r) == nullptr) return FailFromPython();

PyObject *CallBridge(const char *fn, PyObject *args /* stolen */) {
  PyObject *f = PyObject_GetAttrString(Bridge(), fn);
  if (f == nullptr) { Py_XDECREF(args); return nullptr; }
  PyObject *r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  return r;
}

int CreateImpl(const char *symbol_json_str, const void *param_bytes,
               int param_size, int dev_type, int dev_id,
               mx_uint num_input_nodes, const char **input_keys,
               const mx_uint *input_shape_indptr,
               const mx_uint *input_shape_data, mx_uint num_output_nodes,
               const char **output_keys, PredictorHandle *out) {
  PRED_BEGIN();
  PyObject *keys = PyList_New(num_input_nodes);
  PyObject *shapes = PyList_New(num_input_nodes);
  for (mx_uint i = 0; i < num_input_nodes; ++i) {
    PyList_SET_ITEM(keys, i, PyUnicode_FromString(input_keys[i]));
    mx_uint b = input_shape_indptr[i], e = input_shape_indptr[i + 1];
    PyObject *s = PyList_New(e - b);
    for (mx_uint j = b; j < e; ++j)
      PyList_SET_ITEM(s, j - b, PyLong_FromUnsignedLong(input_shape_data[j]));
    PyList_SET_ITEM(shapes, i, s);
  }
  PyObject *outs;
  if (num_output_nodes > 0) {
    outs = PyList_New(num_output_nodes);
    for (mx_uint i = 0; i < num_output_nodes; ++i)
      PyList_SET_ITEM(outs, i, PyUnicode_FromString(output_keys[i]));
  } else {
    outs = Py_None;
    Py_INCREF(outs);
  }
  PyObject *blob = PyBytes_FromStringAndSize(
      (const char *)param_bytes, param_bytes ? param_size : 0);
  PyObject *r = CallBridge(
      "pred_create", Py_BuildValue("(sNiiNNN)", symbol_json_str, blob,
                                   dev_type, dev_id, keys, shapes, outs));
  CHECK_PYP(r);
  *out = (PredictorHandle)r;
  return 0;
}

}  // namespace

extern "C" {

int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char **input_keys,
                 const mx_uint *input_shape_indptr,
                 const mx_uint *input_shape_data, PredictorHandle *out) {
  return CreateImpl(symbol_json_str, param_bytes, param_size, dev_type,
                    dev_id, num_input_nodes, input_keys, input_shape_indptr,
                    input_shape_data, 0, nullptr, out);
}

int MXPredCreatePartialOut(const char *symbol_json_str,
                           const void *param_bytes, int param_size,
                           int dev_type, int dev_id, mx_uint num_input_nodes,
                           const char **input_keys,
                           const mx_uint *input_shape_indptr,
                           const mx_uint *input_shape_data,
                           mx_uint num_output_nodes, const char **output_keys,
                           PredictorHandle *out) {
  return CreateImpl(symbol_json_str, param_bytes, param_size, dev_type,
                    dev_id, num_input_nodes, input_keys, input_shape_indptr,
                    input_shape_data, num_output_nodes, output_keys, out);
}

int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint **shape_data, mx_uint *shape_ndim) {
  PRED_BEGIN();
  PyObject *r = PyObject_CallMethod((PyObject *)handle, "get_output_shape",
                                    "I", index);
  CHECK_PYP(r);
  Py_ssize_t n = PyTuple_Size(r);
  pred_shape.clear();
  for (Py_ssize_t i = 0; i < n; ++i)
    pred_shape.push_back((mx_uint)PyLong_AsUnsignedLong(PyTuple_GET_ITEM(r, i)));
  Py_DECREF(r);
  *shape_data = pred_shape.data();
  *shape_ndim = (mx_uint)n;
  return 0;
}

int MXPredSetInput(PredictorHandle handle, const char *key,
                   const mx_float *data, mx_uint size) {
  PRED_BEGIN();
  PyObject *buf = PyBytes_FromStringAndSize((const char *)data,
                                            (Py_ssize_t)size * 4);
  /* shape comes from the bound input array: pass flat, bridge reshapes */
  PyObject *arr_shape = PyObject_GetAttrString((PyObject *)handle, "args");
  if (arr_shape == nullptr) { Py_DECREF(buf); return FailFromPython(); }
  PyObject *arr = PyDict_GetItemString(arr_shape, key); /* borrowed */
  Py_DECREF(arr_shape);
  PyObject *shape = arr ? PyObject_GetAttrString(arr, "shape") : nullptr;
  if (shape == nullptr) {
    Py_DECREF(buf);
    mxtpu_capi::SetError(std::string("unknown input key: ") + key);
    return -1;
  }
  PyObject *r = PyObject_CallMethod((PyObject *)handle, "set_input", "sNN",
                                    key, buf, shape);
  CHECK_PYP(r); Py_DECREF(r);
  return 0;
}

int MXPredForward(PredictorHandle handle) {
  PRED_BEGIN();
  PyObject *r = PyObject_CallMethod((PyObject *)handle, "forward", nullptr);
  CHECK_PYP(r); Py_DECREF(r);
  return 0;
}

int MXPredPartialForward(PredictorHandle handle, int step, int *step_left) {
  PRED_BEGIN();
  PyObject *r = PyObject_CallMethod((PyObject *)handle, "partial_forward",
                                    "i", step);
  CHECK_PYP(r);
  long left = PyLong_AsLong(r);
  Py_DECREF(r);
  if (left < 0 && PyErr_Occurred()) return FailFromPython();
  *step_left = (int)left;
  return 0;
}

int MXPredGetOutput(PredictorHandle handle, mx_uint index, mx_float *data,
                    mx_uint size) {
  PRED_BEGIN();
  PyObject *r = PyObject_CallMethod((PyObject *)handle, "get_output", "I",
                                    index);
  CHECK_PYP(r);
  char *buf; Py_ssize_t len;
  if (PyBytes_AsStringAndSize(r, &buf, &len) != 0) {
    Py_DECREF(r);
    return FailFromPython();
  }
  if ((size_t)len > (size_t)size * 4) {
    Py_DECREF(r);
    mxtpu_capi::SetError("MXPredGetOutput: buffer too small");
    return -1;
  }
  std::memcpy(data, buf, (size_t)len);
  Py_DECREF(r);
  return 0;
}

int MXPredFree(PredictorHandle handle) {
  if (handle) { Gil g; Py_DECREF((PyObject *)handle); }
  return 0;
}

int MXNDListCreate(const char *nd_file_bytes, int nd_file_size,
                   NDListHandle *out, mx_uint *out_length) {
  PRED_BEGIN();
  PyObject *blob = PyBytes_FromStringAndSize(nd_file_bytes, nd_file_size);
  PyObject *r = CallBridge("nd_list_create", Py_BuildValue("(N)", blob));
  CHECK_PYP(r);
  auto *lst = new NDList();
  lst->keys = PyTuple_GET_ITEM(r, 0);
  Py_INCREF(lst->keys);
  lst->arrays = PyTuple_GET_ITEM(r, 1);
  Py_INCREF(lst->arrays);
  *out_length = (mx_uint)PySequence_Size(lst->arrays);
  Py_DECREF(r);
  *out = (NDListHandle)lst;
  return 0;
}

int MXNDListGet(NDListHandle handle, mx_uint index, const char **out_key,
                const mx_float **out_data, const mx_uint **out_shape,
                mx_uint *out_ndim) {
  PRED_BEGIN();
  auto *lst = (NDList *)handle;
  PyObject *r = CallBridge(
      "nd_list_get", Py_BuildValue("(OOI)", lst->keys, lst->arrays, index));
  CHECK_PYP(r);
  /* (key, fp32 bytes, shape tuple) */
  lst->cur_key = PyUnicode_AsUTF8(PyTuple_GET_ITEM(r, 0));
  char *buf; Py_ssize_t len;
  PyBytes_AsStringAndSize(PyTuple_GET_ITEM(r, 1), &buf, &len);
  lst->cur_data.assign(buf, len);
  PyObject *shape = PyTuple_GET_ITEM(r, 2);
  lst->cur_shape.clear();
  for (Py_ssize_t i = 0; i < PyTuple_Size(shape); ++i)
    lst->cur_shape.push_back(
        (mx_uint)PyLong_AsUnsignedLong(PyTuple_GET_ITEM(shape, i)));
  Py_DECREF(r);
  *out_key = lst->cur_key.c_str();
  *out_data = (const mx_float *)lst->cur_data.data();
  *out_shape = lst->cur_shape.data();
  *out_ndim = (mx_uint)lst->cur_shape.size();
  return 0;
}

int MXNDListFree(NDListHandle handle) {
  if (handle) {
    Gil g;
    auto *lst = (NDList *)handle;
    Py_XDECREF(lst->keys);
    Py_XDECREF(lst->arrays);
    delete lst;
  }
  return 0;
}

}  /* extern "C" */
