/*
 * engine.cc — async read/write-set dependency scheduler.
 *
 * TPU-native rebuild of src/engine/threaded_engine.{h,cc} +
 * threaded_engine_perdevice.cc. The reference schedules *all* compute
 * through this structure; here XLA owns device scheduling, so the
 * engine's job is host-side async work (IO decode/prefetch, checkpoint
 * writes, KVStore host ops) with the same semantics:
 *
 * - ops declare const_vars (reads) and mutable_vars (writes)
 *   (reference engine.h:93-268 PushAsync);
 * - per var, writers are serialized and ordered against readers in
 *   arrival order (reference threaded_engine.h:111-213 ThreadedVar's
 *   VersionedVarBlock list);
 * - ops become ready when every var grants access (OprBlock wait
 *   counter, threaded_engine.h:62-89), then run on a worker pool
 *   ordered by (-priority, fifo seq) — the reference's priority queue
 *   (kvstore pushes grads with priority=-index so front layers sync
 *   first, kvstore.py:139);
 * - WaitForVar pushes a read op that signals (threaded_engine.cc:332);
 * - MXTPU_ENGINE_WORKERS<=0 or num_workers==0 degrades to synchronous
 *   execution (the reference's NaiveEngine, engine.cc:32-48).
 */
#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <queue>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "mxtpu.h"

namespace mxtpu {

void ProfilerRecordOpr(const std::string &name, int64_t start_us,
                       int64_t end_us, int thread_id);
bool ProfilerRunning();
int64_t NowUS();

namespace engine {

struct Opr;

// Per-variable dependency queue (reference ThreadedVar).
struct Var {
  std::mutex mu;
  // pending ops in arrival order; .second = is_write
  std::deque<std::pair<Opr *, bool>> pending;
  int running_reads = 0;
  bool running_write = false;
  bool to_delete = false;  // set by the scheduled delete op
};

struct Opr {
  std::function<void(CompletionHandle)> fn;  // calls complete itself if async
  std::vector<Var *> reads;
  std::vector<Var *> writes;
  std::atomic<int> wait{0};
  int priority = 0;
  bool async = false;
  std::string name;
  class Engine *engine = nullptr;
};

class Engine {
 public:
  explicit Engine(int num_workers) {
    if (num_workers < 0) num_workers = 0;
    num_workers_ = num_workers;
    for (int i = 0; i < num_workers; ++i) {
      workers_.emplace_back([this, i] { WorkerLoop(i); });
    }
  }

  ~Engine() {
    WaitForAll();
    {
      std::lock_guard<std::mutex> lk(qmu_);
      shutdown_ = true;
    }
    qcv_.notify_all();
    for (auto &t : workers_) t.join();
  }

  Var *NewVar() { return new Var(); }

  void Push(std::function<void(CompletionHandle)> fn,
            const std::vector<Var *> &reads,
            const std::vector<Var *> &writes, int priority, bool async,
            const char *name) {
    auto *opr = new Opr();
    opr->fn = std::move(fn);
    opr->engine = this;
    // dedupe and drop reads that are also writes (reference engine.h
    // :249-267 deduplication helper; duplicate vars would deadlock the
    // grant accounting)
    std::set<Var *> wset(writes.begin(), writes.end());
    std::set<Var *> rset;
    for (Var *v : reads)
      if (!wset.count(v)) rset.insert(v);
    opr->reads.assign(rset.begin(), rset.end());
    opr->writes.assign(wset.begin(), wset.end());
    opr->priority = priority;
    opr->async = async;
    if (name) opr->name = name;
    pending_.fetch_add(1, std::memory_order_relaxed);

    int deps = static_cast<int>(opr->reads.size() + opr->writes.size());
    opr->wait.store(deps + 1, std::memory_order_relaxed);  // +1 = push guard
    {
      // registration of the whole read/write set is atomic wrt other
      // pushes: without this, two concurrently-pushed ops with crossing
      // sets (op1 r:A w:B, op2 r:B w:A — possible since ctypes releases
      // the GIL) can each hold a grant the other's write needs, a silent
      // scheduler deadlock. The reference registers from one thread.
      std::lock_guard<std::mutex> plk(push_mu_);
      for (Var *v : opr->reads) RequestAccess(opr, v, false);
      for (Var *v : opr->writes) RequestAccess(opr, v, true);
    }
    // release push guard; if all vars granted already, schedule now
    if (opr->wait.fetch_sub(1, std::memory_order_acq_rel) == 1) Schedule(opr);
  }

  void DeleteVar(Var *var) {
    // mark first, then schedule a write op; the var is freed when its
    // final access (this op or a later-granted one) releases
    {
      std::lock_guard<std::mutex> lk(var->mu);
      var->to_delete = true;
    }
    Push([](CompletionHandle) {}, {}, {var}, 0, false, "DeleteVariable");
  }

  void WaitForVar(Var *var) {
    std::mutex mu;
    std::condition_variable cv;
    bool done = false;
    Push(
        [&](CompletionHandle) {
          std::lock_guard<std::mutex> lk(mu);
          done = true;
          cv.notify_all();
        },
        {var}, {}, 0x7fffffff, false, "WaitForVar");
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [&] { return done; });
  }

  void WaitForAll() {
    std::unique_lock<std::mutex> lk(finish_mu_);
    finish_cv_.wait(lk, [this] {
      return pending_.load(std::memory_order_acquire) == 0;
    });
  }

  int64_t PendingOps() { return pending_.load(std::memory_order_acquire); }

  void OnComplete(Opr *opr) {
    for (Var *v : opr->reads) ReleaseRead(v);
    for (Var *v : opr->writes) ReleaseWrite(v);
    delete opr;
    if (pending_.fetch_sub(1, std::memory_order_acq_rel) == 1) {
      std::lock_guard<std::mutex> lk(finish_mu_);
      finish_cv_.notify_all();
    }
  }

 private:
  void RequestAccess(Opr *opr, Var *v, bool write) {
    bool granted = false;
    {
      std::lock_guard<std::mutex> lk(v->mu);
      if (write) {
        if (!v->running_write && v->running_reads == 0 &&
            v->pending.empty()) {
          v->running_write = true;
          granted = true;
        } else {
          v->pending.emplace_back(opr, true);
        }
      } else {
        if (!v->running_write && v->pending.empty()) {
          ++v->running_reads;
          granted = true;
        } else {
          v->pending.emplace_back(opr, false);
        }
      }
    }
    if (granted) Grant(opr);
  }

  void Grant(Opr *opr) {
    if (opr->wait.fetch_sub(1, std::memory_order_acq_rel) == 1)
      Schedule(opr);
  }

  void ReleaseRead(Var *v) {
    std::vector<Opr *> to_grant;
    bool del = false;
    {
      std::lock_guard<std::mutex> lk(v->mu);
      --v->running_reads;
      DrainLocked(v, &to_grant);
      del = Deletable(v);
    }
    for (Opr *o : to_grant) Grant(o);
    if (del) delete v;
  }

  void ReleaseWrite(Var *v) {
    std::vector<Opr *> to_grant;
    bool del = false;
    {
      std::lock_guard<std::mutex> lk(v->mu);
      v->running_write = false;
      DrainLocked(v, &to_grant);
      del = Deletable(v);
    }
    for (Opr *o : to_grant) Grant(o);
    if (del) delete v;
  }

  static bool Deletable(Var *v) {
    return v->to_delete && v->pending.empty() && v->running_reads == 0 &&
           !v->running_write;
  }

  // grant from the front of the queue: a run of readers, or one writer
  static void DrainLocked(Var *v, std::vector<Opr *> *out) {
    while (!v->pending.empty()) {
      auto [opr, is_write] = v->pending.front();
      if (is_write) {
        if (v->running_reads == 0 && !v->running_write) {
          v->running_write = true;
          v->pending.pop_front();
          out->push_back(opr);
        }
        break;  // writer blocks everything behind it
      }
      if (v->running_write) break;
      ++v->running_reads;
      v->pending.pop_front();
      out->push_back(opr);
    }
  }

  void Schedule(Opr *opr) {
    if (num_workers_ == 0) {  // NaiveEngine: run inline
      Execute(opr, -1);
      return;
    }
    {
      std::lock_guard<std::mutex> lk(qmu_);
      ready_.push(Entry{opr, seq_++});
    }
    qcv_.notify_one();
  }

  struct Entry {
    Opr *opr;
    uint64_t seq;
    bool operator<(const Entry &o) const {
      if (opr->priority != o.opr->priority)
        return opr->priority < o.opr->priority;  // max-heap on priority
      return seq > o.seq;                        // FIFO within priority
    }
  };

  void Execute(Opr *opr, int thread_id) {
    bool prof = ProfilerRunning();
    int64_t t0 = prof ? NowUS() : 0;
    std::string name = prof ? opr->name : std::string();
    if (opr->async) {
      // fn may call MXTEngineOprComplete inline, freeing opr — no
      // member access after this call; the recorded span is submit time
      opr->fn(reinterpret_cast<CompletionHandle>(opr));
      if (prof) ProfilerRecordOpr(name, t0, NowUS(), thread_id);
    } else {
      opr->fn(nullptr);
      if (prof) ProfilerRecordOpr(name, t0, NowUS(), thread_id);
      OnComplete(opr);
    }
  }

  void WorkerLoop(int tid) {
    for (;;) {
      Opr *opr = nullptr;
      {
        std::unique_lock<std::mutex> lk(qmu_);
        qcv_.wait(lk, [this] { return shutdown_ || !ready_.empty(); });
        if (shutdown_ && ready_.empty()) return;
        opr = ready_.top().opr;
        ready_.pop();
      }
      Execute(opr, tid);
    }
  }

  int num_workers_;
  std::vector<std::thread> workers_;
  std::mutex qmu_;
  std::mutex push_mu_;  // serializes dependency registration (see Push)
  std::condition_variable qcv_;
  std::priority_queue<Entry> ready_;
  uint64_t seq_ = 0;
  bool shutdown_ = false;
  std::atomic<int64_t> pending_{0};
  std::mutex finish_mu_;
  std::condition_variable finish_cv_;
};

}  // namespace engine
}  // namespace mxtpu

/* ---------------- C API ---------------- */

namespace {
thread_local std::string g_last_error;
}  // namespace

void MXTSetLastError(const char *msg) { g_last_error = msg ? msg : ""; }

extern "C" const char *MXTGetLastError() { return g_last_error.c_str(); }

#define API_BEGIN() try {
#define API_END()                        \
  }                                      \
  catch (const std::exception &e) {      \
    g_last_error = e.what();             \
    return -1;                           \
  }                                      \
  catch (...) {                          \
    g_last_error = "unknown native error"; \
    return -1;                           \
  }                                      \
  return 0;

using mxtpu::engine::Engine;
using mxtpu::engine::Opr;
using mxtpu::engine::Var;

extern "C" int MXTEngineCreate(int num_workers, EngineHandle *out) {
  API_BEGIN();
  *out = new Engine(num_workers);
  API_END();
}

extern "C" int MXTEngineFree(EngineHandle h) {
  API_BEGIN();
  delete static_cast<Engine *>(h);
  API_END();
}

extern "C" int MXTEngineNewVar(EngineHandle h, VarHandle *out) {
  API_BEGIN();
  *out = static_cast<Engine *>(h)->NewVar();
  API_END();
}

extern "C" int MXTEngineDeleteVar(EngineHandle h, VarHandle var) {
  API_BEGIN();
  static_cast<Engine *>(h)->DeleteVar(static_cast<Var *>(var));
  API_END();
}

static int PushImpl(EngineHandle h, std::function<void(CompletionHandle)> fn,
                    VarHandle *const_vars, int num_const,
                    VarHandle *mutable_vars, int num_mutable, int priority,
                    const char *name, bool async) {
  API_BEGIN();
  std::vector<Var *> reads, writes;
  for (int i = 0; i < num_const; ++i)
    reads.push_back(static_cast<Var *>(const_vars[i]));
  for (int i = 0; i < num_mutable; ++i)
    writes.push_back(static_cast<Var *>(mutable_vars[i]));
  static_cast<Engine *>(h)->Push(std::move(fn), reads, writes, priority,
                                 async, name);
  API_END();
}

extern "C" int MXTEnginePushSync(EngineHandle h, MXTSyncFn fn, void *param,
                                 VarHandle *const_vars, int num_const,
                                 VarHandle *mutable_vars, int num_mutable,
                                 int priority, const char *opr_name) {
  return PushImpl(
      h, [fn, param](CompletionHandle) { fn(param); }, const_vars, num_const,
      mutable_vars, num_mutable, priority, opr_name, false);
}

extern "C" int MXTEnginePushAsync(EngineHandle h, MXTAsyncFn fn, void *param,
                                  VarHandle *const_vars, int num_const,
                                  VarHandle *mutable_vars, int num_mutable,
                                  int priority, const char *opr_name) {
  return PushImpl(
      h, [fn, param](CompletionHandle c) { fn(param, c); }, const_vars,
      num_const, mutable_vars, num_mutable, priority, opr_name, true);
}

extern "C" int MXTEngineOprComplete(CompletionHandle token) {
  API_BEGIN();
  Opr *opr = static_cast<Opr *>(token);
  opr->engine->OnComplete(opr);
  API_END();
}

extern "C" int MXTEngineWaitForVar(EngineHandle h, VarHandle var) {
  API_BEGIN();
  static_cast<Engine *>(h)->WaitForVar(static_cast<Var *>(var));
  API_END();
}

extern "C" int MXTEngineWaitForAll(EngineHandle h) {
  API_BEGIN();
  static_cast<Engine *>(h)->WaitForAll();
  API_END();
}

extern "C" int MXTEnginePendingOps(EngineHandle h, int64_t *out) {
  API_BEGIN();
  *out = static_cast<Engine *>(h)->PendingOps();
  API_END();
}
