/*
 * storage.cc — pooled, aligned host storage manager.
 *
 * TPU-native rebuild of src/storage/storage.cc + pooled_storage_manager.h
 * (reference GPUPooledStorageManager: size-bucketed free lists so repeated
 * alloc/free of the same shapes never hits the system allocator). On TPU
 * the device pool belongs to the XLA runtime; this manager serves the
 * host side: staging buffers for IO decode, RecordIO batch assembly, and
 * pinned-style scratch for host<->device transfers.
 */
#include <cstdint>
#include <cstdlib>
#include <mutex>
#include <new>
#include <stdexcept>
#include <unordered_map>
#include <vector>

#include "mxtpu.h"

namespace mxtpu {
namespace storage {

constexpr size_t kAlign = 64;  // cache line; also good for dma staging

class PooledStorage {
 public:
  static PooledStorage *Get() {
    static PooledStorage inst;
    return &inst;
  }

  void *Alloc(size_t nbytes) {
    size_t bucket = RoundUp(nbytes);
    {
      std::lock_guard<std::mutex> lk(mu_);
      ++alloc_calls_;
      auto it = pool_.find(bucket);
      if (it != pool_.end() && !it->second.empty()) {
        void *p = it->second.back();
        it->second.pop_back();
        pooled_bytes_ -= bucket;
        live_bytes_ += bucket;
        ++pool_hits_;
        size_of_[p] = bucket;
        return p;
      }
    }
    void *p = ::aligned_alloc(kAlign, bucket);
    if (!p) throw std::bad_alloc();
    std::lock_guard<std::mutex> lk(mu_);
    live_bytes_ += bucket;
    size_of_[p] = bucket;
    return p;
  }

  void Free(void *p) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = size_of_.find(p);
    if (it == size_of_.end())
      throw std::runtime_error("MXTStorageFree: unknown pointer");
    size_t bucket = it->second;
    size_of_.erase(it);
    live_bytes_ -= bucket;
    pooled_bytes_ += bucket;
    pool_[bucket].push_back(p);
  }

  void DirectFree(void *p) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = size_of_.find(p);
      if (it == size_of_.end())
        throw std::runtime_error("MXTStorageDirectFree: unknown pointer");
      live_bytes_ -= it->second;
      size_of_.erase(it);
    }
    ::free(p);
  }

  void ReleaseAll() {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto &kv : pool_)
      for (void *p : kv.second) ::free(p);
    pool_.clear();
    pooled_bytes_ = 0;
  }

  void Stats(int64_t out[4]) {
    std::lock_guard<std::mutex> lk(mu_);
    out[0] = static_cast<int64_t>(live_bytes_);
    out[1] = static_cast<int64_t>(pooled_bytes_);
    out[2] = alloc_calls_;
    out[3] = pool_hits_;
  }

 private:
  // next power of two, min 256B — same shape-bucketing idea as the
  // reference's pool (pooled_storage_manager.h:46)
  static size_t RoundUp(size_t n) {
    size_t b = 256;
    while (b < n) b <<= 1;
    return b;
  }

  std::mutex mu_;
  std::unordered_map<size_t, std::vector<void *>> pool_;
  std::unordered_map<void *, size_t> size_of_;
  size_t live_bytes_ = 0;
  size_t pooled_bytes_ = 0;
  int64_t alloc_calls_ = 0;
  int64_t pool_hits_ = 0;
};

}  // namespace storage
}  // namespace mxtpu

void MXTSetLastError(const char *msg);

#define API_BEGIN() try {
#define API_END()                  \
  }                                \
  catch (const std::exception &e) { \
    MXTSetLastError(e.what());     \
    return -1;                     \
  }                                \
  return 0;

using mxtpu::storage::PooledStorage;

extern "C" int MXTStorageAlloc(size_t nbytes, void **out) {
  API_BEGIN();
  *out = PooledStorage::Get()->Alloc(nbytes);
  API_END();
}

extern "C" int MXTStorageFree(void *ptr) {
  API_BEGIN();
  PooledStorage::Get()->Free(ptr);
  API_END();
}

extern "C" int MXTStorageDirectFree(void *ptr) {
  API_BEGIN();
  PooledStorage::Get()->DirectFree(ptr);
  API_END();
}

extern "C" int MXTStorageReleaseAll() {
  API_BEGIN();
  PooledStorage::Get()->ReleaseAll();
  API_END();
}

extern "C" int MXTStorageStats(int64_t stats[4]) {
  API_BEGIN();
  PooledStorage::Get()->Stats(stats);
  API_END();
}
