/*
 * c_api.cc — C ABI over the CPython-hosted XLA core.
 *
 * Reference: src/c_api/c_api.cc, c_api_symbolic.cc, c_api_executor.cc
 * (handle marshalling + thread-local error/return storage around the
 * C++ core). Here the core is mxnet_tpu (JAX/XLA); the library embeds
 * the interpreter lazily and each entry point calls one helper in
 * mxnet_tpu._c_api_impl, holding the GIL only for the call. Handles
 * are new references to CPython objects; MX*Free drops them.
 */
#include <Python.h>

#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "../include/mxnet_tpu/c_api.h"
#include "mxtpu.h"

namespace {

thread_local std::string last_error;

/* thread-local return storage (reference: MXAPIThreadLocalEntry) */
struct RetStore {
  std::vector<std::string> strings;
  std::vector<const char *> cptrs;
  std::vector<mx_uint> shape;
  std::vector<int> ints;
  std::vector<void *> handles;
  std::string blob;
  /* CSR shape returns for InferShape */
  std::vector<mx_uint> ndims[3];
  std::vector<std::vector<mx_uint>> dims[3];
  std::vector<const mx_uint *> dptr[3];
  std::vector<int> types[3];
};
thread_local RetStore ret;

PyObject *bridge = nullptr;  /* mxnet_tpu._c_api_impl, owned */
std::once_flag init_flag;
bool init_ok = false;
bool we_initialized_python = false;

void InitPython() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    we_initialized_python = true;
  }
  PyGILState_STATE g = PyGILState_Ensure();
  bridge = PyImport_ImportModule("mxnet_tpu._c_api_impl");
  if (bridge == nullptr) {
    PyObject *t, *v, *tb;
    PyErr_Fetch(&t, &v, &tb);
    PyObject *s = v ? PyObject_Str(v) : nullptr;
    last_error = std::string("failed to import mxnet_tpu._c_api_impl: ") +
                 (s && PyUnicode_Check(s) ? PyUnicode_AsUTF8(s) : "?");
    Py_XDECREF(s); Py_XDECREF(t); Py_XDECREF(v); Py_XDECREF(tb);
  } else {
    init_ok = true;
  }
  if (we_initialized_python) {
    /* release the GIL so any thread can PyGILState_Ensure later */
    PyGILState_Release(g);
    PyEval_SaveThread();
  } else {
    PyGILState_Release(g);
  }
}

/* guarded so the amalgamated single-TU build (amalgamation/) sees one
 * definition; c_predict_api.cc carries the same block */
#ifndef MXTPU_GIL_DEFINED
#define MXTPU_GIL_DEFINED
struct Gil {
  PyGILState_STATE state;
  Gil() { state = PyGILState_Ensure(); }
  ~Gil() { PyGILState_Release(state); }
};
#endif

int Fail() {
  PyObject *t = nullptr, *v = nullptr, *tb = nullptr;
  PyErr_Fetch(&t, &v, &tb);
  PyErr_NormalizeException(&t, &v, &tb);
  PyObject *s = v ? PyObject_Str(v) : nullptr;
  last_error = (s && PyUnicode_Check(s)) ? PyUnicode_AsUTF8(s)
                                         : "unknown python error";
  Py_XDECREF(s); Py_XDECREF(t); Py_XDECREF(v); Py_XDECREF(tb);
  return -1;
}

bool Ensure() {
  std::call_once(init_flag, InitPython);
  if (!init_ok && last_error.empty())
    last_error = "mxnet_tpu C API: interpreter init failed";
  return init_ok;
}

/* Call bridge.<fn>(args tuple). Returns new ref or nullptr. */
PyObject *CallV(const char *fn, PyObject *args /* stolen */) {
  PyObject *f = PyObject_GetAttrString(bridge, fn);
  if (f == nullptr) { Py_XDECREF(args); return nullptr; }
  PyObject *r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  return r;
}

PyObject *HandleList(int n, void *const *handles) {
  PyObject *l = PyList_New(n);
  for (int i = 0; i < n; ++i) {
    PyObject *h = handles && handles[i] ? (PyObject *)handles[i] : Py_None;
    Py_INCREF(h);
    PyList_SET_ITEM(l, i, h);
  }
  return l;
}

PyObject *StrList(int n, const char *const *strs) {
  PyObject *l = PyList_New(n);
  for (int i = 0; i < n; ++i)
    PyList_SET_ITEM(l, i, PyUnicode_FromString(strs && strs[i] ? strs[i] : ""));
  return l;
}

PyObject *IntList(int n, const int *v) {
  PyObject *l = PyList_New(n);
  for (int i = 0; i < n; ++i)
    PyList_SET_ITEM(l, i, PyLong_FromLong(v ? v[i] : 0));
  return l;
}

PyObject *UIntList(int n, const mx_uint *v) {
  PyObject *l = PyList_New(n);
  for (int i = 0; i < n; ++i)
    PyList_SET_ITEM(l, i, PyLong_FromUnsignedLong(v ? v[i] : 0));
  return l;
}

/* Store a python str list into thread-local storage; returns char**. */
const char **StoreStrList(PyObject *list, mx_uint *out_size) {
  Py_ssize_t n = PySequence_Size(list);
  ret.strings.clear();
  ret.strings.reserve(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *it = PySequence_GetItem(list, i);
    ret.strings.emplace_back(PyUnicode_Check(it) ? PyUnicode_AsUTF8(it) : "");
    Py_DECREF(it);
  }
  ret.cptrs.clear();
  for (auto &s : ret.strings) ret.cptrs.push_back(s.c_str());
  *out_size = (mx_uint)n;
  return ret.cptrs.data();
}

void **StoreHandleList(PyObject *list, mx_uint *out_size) {
  Py_ssize_t n = PySequence_Size(list);
  ret.handles.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *it = PySequence_GetItem(list, i); /* new ref, kept */
    ret.handles.push_back((void *)it);
  }
  *out_size = (mx_uint)n;
  return ret.handles.data();
}

#define API_BEGIN() \
  if (!Ensure()) return -1; \
  Gil gil_;
#define CHECK_PY(r) if ((r) == nullptr) return Fail();

}  // namespace

/* shared with c_predict_api.cc */
namespace mxtpu_capi {
bool EnsureBridge() { return Ensure(); }
PyObject *Bridge() { return bridge; }
int FailFromPython() { return Fail(); }
void SetError(const std::string &msg) { last_error = msg; }
}  // namespace mxtpu_capi

extern "C" {

const char *MXGetLastError() { return last_error.c_str(); }

/* ------------------------------------------------------------- misc -- */

int MXGetVersion(int *out) { *out = 20000; return 0; }

int MXRandomSeed(int seed) {
  API_BEGIN();
  PyObject *r = CallV("random_seed", Py_BuildValue("(i)", seed));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXNotifyShutdown() {
  API_BEGIN();
  PyObject *r = CallV("notify_shutdown", PyTuple_New(0));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXSetNumOMPThreads(int) { return 0; }

int MXSetProfilerConfig(int mode, const char *filename) {
  API_BEGIN();
  PyObject *r = CallV("profiler_set_config",
                      Py_BuildValue("(is)", mode, filename));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXSetProfilerState(int state) {
  API_BEGIN();
  PyObject *r = CallV("profiler_set_state", Py_BuildValue("(i)", state));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXDumpProfile() {
  API_BEGIN();
  PyObject *r = CallV("profiler_dump", PyTuple_New(0));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

/* ---------------------------------------------------------- ndarray -- */

int MXNDArrayCreateNone(NDArrayHandle *out) {
  API_BEGIN();
  PyObject *r = CallV("nd_create_none", PyTuple_New(0));
  CHECK_PY(r);
  *out = (NDArrayHandle)r;
  return 0;
}

static int CreateImpl(const mx_uint *shape, mx_uint ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle *out) {
  API_BEGIN();
  PyObject *shp = UIntList((int)ndim, shape);
  PyObject *r = CallV("nd_create", Py_BuildValue("(Niiii)", shp, dev_type,
                                                 dev_id, delay_alloc, dtype));
  CHECK_PY(r);
  *out = (NDArrayHandle)r;
  return 0;
}

int MXNDArrayCreate(const mx_uint *shape, mx_uint ndim, int dev_type,
                    int dev_id, int delay_alloc, NDArrayHandle *out) {
  return CreateImpl(shape, ndim, dev_type, dev_id, delay_alloc, 0, out);
}

int MXNDArrayCreateEx(const mx_uint *shape, mx_uint ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle *out) {
  return CreateImpl(shape, ndim, dev_type, dev_id, delay_alloc, dtype, out);
}

int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void *data,
                             size_t size) {
  API_BEGIN();
  PyObject *h = (PyObject *)handle;
  PyObject *dt = CallV("nd_dtype", Py_BuildValue("(O)", h));
  CHECK_PY(dt);
  long dtype = PyLong_AsLong(dt);
  Py_DECREF(dt);
  /* size is an element count in the reference ABI; bytes are in the
   * array's own dtype (bf16 = 2 B/elt, matching MXNDArrayGetDType) */
  static const size_t esize[] = {4, 8, 2, 1, 4, 1, 8, 2};
  size_t nbytes = size * esize[dtype < 8 ? dtype : 0];
  PyObject *buf = PyBytes_FromStringAndSize((const char *)data, nbytes);
  PyObject *r = CallV("nd_sync_copy_from_bytes",
                      Py_BuildValue("(ONl)", h, buf, dtype));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data, size_t size) {
  API_BEGIN();
  PyObject *dt = CallV("nd_dtype", Py_BuildValue("(O)", (PyObject *)handle));
  CHECK_PY(dt);
  long dtype = PyLong_AsLong(dt);
  Py_DECREF(dt);
  static const size_t esize[] = {4, 8, 2, 1, 4, 1, 8, 2};
  size_t expect = size * esize[(dtype >= 0 && dtype < 8) ? dtype : 0];
  PyObject *r = CallV("nd_sync_copy_to_bytes",
                      Py_BuildValue("(O)", (PyObject *)handle));
  CHECK_PY(r);
  char *buf; Py_ssize_t len;
  if (PyBytes_AsStringAndSize(r, &buf, &len) != 0) { Py_DECREF(r); return Fail(); }
  /* size is the caller's element count; refuse mismatches instead of
   * overrunning the caller's buffer (reference: CHECK_EQ on Size()) */
  if ((size_t)len != expect) {
    Py_DECREF(r);
    last_error = "MXNDArraySyncCopyToCPU: element count/dtype mismatch";
    return -1;
  }
  std::memcpy(data, buf, (size_t)len);
  Py_DECREF(r);
  return 0;
}

int MXNDArrayWaitToRead(NDArrayHandle handle) {
  API_BEGIN();
  PyObject *r = CallV("nd_wait_to_read", Py_BuildValue("(O)", (PyObject *)handle));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXNDArrayWaitToWrite(NDArrayHandle handle) {
  return MXNDArrayWaitToRead(handle);
}

int MXNDArrayWaitAll() {
  API_BEGIN();
  PyObject *r = CallV("nd_wait_all", PyTuple_New(0));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXNDArrayFree(NDArrayHandle handle) {
  if (handle == nullptr) return 0;
  API_BEGIN();
  PyObject *r = CallV("nd_free", Py_BuildValue("(O)", (PyObject *)handle));
  Py_XDECREF(r);
  if (r == nullptr) PyErr_Clear();
  Py_DECREF((PyObject *)handle);
  return 0;
}

static int UnaryHandleOp(const char *fn, NDArrayHandle h, NDArrayHandle *out) {
  API_BEGIN();
  PyObject *r = CallV(fn, Py_BuildValue("(O)", (PyObject *)h));
  CHECK_PY(r);
  if (r == Py_None) { Py_DECREF(r); *out = nullptr; return 0; }
  *out = (NDArrayHandle)r;
  return 0;
}

int MXNDArraySlice(NDArrayHandle handle, mx_uint begin, mx_uint end,
                   NDArrayHandle *out) {
  API_BEGIN();
  PyObject *r = CallV("nd_slice", Py_BuildValue("(OII)", (PyObject *)handle,
                                                begin, end));
  CHECK_PY(r);
  *out = (NDArrayHandle)r;
  return 0;
}

int MXNDArrayAt(NDArrayHandle handle, mx_uint idx, NDArrayHandle *out) {
  API_BEGIN();
  PyObject *r = CallV("nd_at", Py_BuildValue("(OI)", (PyObject *)handle, idx));
  CHECK_PY(r);
  *out = (NDArrayHandle)r;
  return 0;
}

int MXNDArrayReshape(NDArrayHandle handle, int ndim, const int *dims,
                     NDArrayHandle *out) {
  API_BEGIN();
  PyObject *shp = IntList(ndim, dims);
  PyObject *r = CallV("nd_reshape", Py_BuildValue("(ON)", (PyObject *)handle, shp));
  CHECK_PY(r);
  *out = (NDArrayHandle)r;
  return 0;
}

int MXNDArrayGetShape(NDArrayHandle handle, mx_uint *out_dim,
                      const mx_uint **out_pdata) {
  API_BEGIN();
  PyObject *r = CallV("nd_shape", Py_BuildValue("(O)", (PyObject *)handle));
  CHECK_PY(r);
  Py_ssize_t n = PyTuple_Size(r);
  ret.shape.clear();
  for (Py_ssize_t i = 0; i < n; ++i)
    ret.shape.push_back((mx_uint)PyLong_AsUnsignedLong(PyTuple_GET_ITEM(r, i)));
  Py_DECREF(r);
  *out_dim = (mx_uint)n;
  *out_pdata = ret.shape.data();
  return 0;
}

static int IntGetter(const char *fn, void *handle, int *out) {
  API_BEGIN();
  PyObject *r = CallV(fn, Py_BuildValue("(O)", (PyObject *)handle));
  CHECK_PY(r);
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

int MXNDArrayGetDType(NDArrayHandle handle, int *out) {
  return IntGetter("nd_dtype", handle, out);
}

int MXNDArrayGetStorageType(NDArrayHandle handle, int *out) {
  return IntGetter("nd_stype", handle, out);
}

int MXNDArrayGetContext(NDArrayHandle handle, int *out_dev_type,
                        int *out_dev_id) {
  API_BEGIN();
  PyObject *r = CallV("nd_context", Py_BuildValue("(O)", (PyObject *)handle));
  CHECK_PY(r);
  *out_dev_type = (int)PyLong_AsLong(PyTuple_GET_ITEM(r, 0));
  *out_dev_id = (int)PyLong_AsLong(PyTuple_GET_ITEM(r, 1));
  Py_DECREF(r);
  return 0;
}

int MXNDArrayGetData(NDArrayHandle handle, void **out_pdata) {
  API_BEGIN();
  PyObject *r = CallV("nd_data_ptr", Py_BuildValue("(O)", (PyObject *)handle));
  CHECK_PY(r);
  *out_pdata = (void *)PyLong_AsUnsignedLongLong(r);
  Py_DECREF(r);
  return 0;
}

int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle *out) {
  return UnaryHandleOp("nd_get_grad", handle, out);
}

int MXNDArrayDetach(NDArrayHandle handle, NDArrayHandle *out) {
  return UnaryHandleOp("nd_detach", handle, out);
}

int MXNDArraySave(const char *fname, mx_uint num_args, NDArrayHandle *args,
                  const char **keys) {
  API_BEGIN();
  PyObject *hl = HandleList((int)num_args, args);
  PyObject *kl = keys ? StrList((int)num_args, keys) : (Py_INCREF(Py_None), Py_None);
  PyObject *r = CallV("nd_save", Py_BuildValue("(sNN)", fname, hl, kl));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXNDArrayLoad(const char *fname, mx_uint *out_size,
                  NDArrayHandle **out_arr, mx_uint *out_name_size,
                  const char ***out_names) {
  API_BEGIN();
  PyObject *r = CallV("nd_load", Py_BuildValue("(s)", fname));
  CHECK_PY(r);
  PyObject *keys = PyTuple_GET_ITEM(r, 0);
  PyObject *arrs = PyTuple_GET_ITEM(r, 1);
  *out_names = StoreStrList(keys, out_name_size);
  *out_arr = (NDArrayHandle *)StoreHandleList(arrs, out_size);
  Py_DECREF(r);
  return 0;
}

int MXNDArraySaveRawBytes(NDArrayHandle handle, size_t *out_size,
                          const char **out_buf) {
  API_BEGIN();
  PyObject *r = CallV("nd_save_raw_bytes", Py_BuildValue("(O)", (PyObject *)handle));
  CHECK_PY(r);
  char *buf; Py_ssize_t len;
  if (PyBytes_AsStringAndSize(r, &buf, &len) != 0) { Py_DECREF(r); return Fail(); }
  ret.blob.assign(buf, len);
  Py_DECREF(r);
  *out_size = (size_t)ret.blob.size();
  *out_buf = ret.blob.data();
  return 0;
}

int MXNDArrayLoadFromRawBytes(const void *buf, size_t size,
                              NDArrayHandle *out) {
  API_BEGIN();
  PyObject *b = PyBytes_FromStringAndSize((const char *)buf, (Py_ssize_t)size);
  PyObject *r = CallV("nd_load_from_raw_bytes", Py_BuildValue("(N)", b));
  CHECK_PY(r);
  *out = (NDArrayHandle)r;
  return 0;
}

/* -------------------------------------------------------- operators -- */

/* op-name table doubles as the AtomicSymbolCreator registry (handles are
 * pointers to interned names, as in the reference where creators are
 * nnvm::Op*). */
static std::vector<std::string> *op_names = nullptr;

static int EnsureOpNames() {
  if (op_names != nullptr) return 0;
  PyObject *r = CallV("list_all_op_names", PyTuple_New(0));
  if (r == nullptr) return Fail();
  auto *names = new std::vector<std::string>();
  Py_ssize_t n = PySequence_Size(r);
  names->reserve(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *it = PySequence_GetItem(r, i);
    names->push_back(PyUnicode_AsUTF8(it));
    Py_DECREF(it);
  }
  Py_DECREF(r);
  op_names = names;
  return 0;
}

int MXListAllOpNames(mx_uint *out_size, const char ***out_array) {
  API_BEGIN();
  if (EnsureOpNames() != 0) return -1;
  ret.cptrs.clear();
  for (auto &s : *op_names) ret.cptrs.push_back(s.c_str());
  *out_size = (mx_uint)op_names->size();
  *out_array = ret.cptrs.data();
  return 0;
}

int MXSymbolListAtomicSymbolCreators(mx_uint *out_size,
                                     AtomicSymbolCreator **out_array) {
  API_BEGIN();
  if (EnsureOpNames() != 0) return -1;
  ret.handles.clear();
  for (auto &s : *op_names) ret.handles.push_back((void *)&s);
  *out_size = (mx_uint)op_names->size();
  *out_array = (AtomicSymbolCreator *)ret.handles.data();
  return 0;
}

static const char *CreatorName(AtomicSymbolCreator creator) {
  return ((const std::string *)creator)->c_str();
}

int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator creator,
                                const char **name) {
  *name = CreatorName(creator);
  return 0;
}

int MXSymbolGetAtomicSymbolInfo(AtomicSymbolCreator creator,
                                const char **name, const char **description,
                                mx_uint *num_args, const char ***arg_names,
                                const char ***arg_type_infos,
                                const char ***arg_descriptions,
                                const char **key_var_num_args,
                                const char **return_type) {
  API_BEGIN();
  PyObject *r = CallV("op_info", Py_BuildValue("(s)", CreatorName(creator)));
  CHECK_PY(r);
  /* (name, doc, arg_names, arg_types, arg_descs, key_var_num_args, rtype) */
  ret.strings.clear();
  ret.cptrs.clear();
  auto keep = [&](PyObject *o) {
    ret.strings.emplace_back(PyUnicode_Check(o) ? PyUnicode_AsUTF8(o) : "");
  };
  keep(PyTuple_GET_ITEM(r, 0));
  keep(PyTuple_GET_ITEM(r, 1));
  keep(PyTuple_GET_ITEM(r, 5));
  keep(PyTuple_GET_ITEM(r, 6));
  PyObject *an = PyTuple_GET_ITEM(r, 2);
  PyObject *at = PyTuple_GET_ITEM(r, 3);
  PyObject *ad = PyTuple_GET_ITEM(r, 4);
  Py_ssize_t n = PySequence_Size(an);
  for (PyObject *lst : {an, at, ad})
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject *it = PySequence_GetItem(lst, i);
      keep(it);
      Py_DECREF(it);
    }
  Py_DECREF(r);
  /* pointers into ret.strings (stable until next call on this thread) */
  *name = ret.strings[0].c_str();
  *description = ret.strings[1].c_str();
  *key_var_num_args = ret.strings[2].c_str();
  if (return_type) *return_type = ret.strings[3].c_str();
  *num_args = (mx_uint)n;
  for (size_t i = 4; i < ret.strings.size(); ++i)
    ret.cptrs.push_back(ret.strings[i].c_str());
  *arg_names = ret.cptrs.data();
  *arg_type_infos = ret.cptrs.data() + n;
  *arg_descriptions = ret.cptrs.data() + 2 * n;
  return 0;
}

int MXImperativeInvoke(AtomicSymbolCreator creator, int num_inputs,
                       NDArrayHandle *inputs, int *num_outputs,
                       NDArrayHandle **outputs, int num_params,
                       const char **param_keys, const char **param_vals) {
  API_BEGIN();
  PyObject *ins = HandleList(num_inputs, inputs);
  PyObject *keys = StrList(num_params, param_keys);
  PyObject *vals = StrList(num_params, param_vals);
  int n_provided = (*num_outputs > 0 && *outputs != nullptr) ? *num_outputs : 0;
  PyObject *outs = HandleList(n_provided, (void **)(n_provided ? *outputs : nullptr));
  PyObject *r = CallV("imperative_invoke",
                      Py_BuildValue("(sNNNiN)", CreatorName(creator), ins,
                                    keys, vals, n_provided, outs));
  CHECK_PY(r);
  mx_uint n = 0;
  if (n_provided == 0) {
    *outputs = (NDArrayHandle *)StoreHandleList(r, &n);
    *num_outputs = (int)n;
  } else {
    *num_outputs = (int)PySequence_Size(r);
  }
  Py_DECREF(r);
  return 0;
}

/* --------------------------------------------------------- autograd -- */

int MXAutogradSetIsRecording(int is_recording, int *prev) {
  API_BEGIN();
  PyObject *r = CallV("autograd_set_recording", Py_BuildValue("(i)", is_recording));
  CHECK_PY(r);
  if (prev) *prev = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

int MXAutogradSetIsTraining(int is_training, int *prev) {
  API_BEGIN();
  PyObject *r = CallV("autograd_set_training", Py_BuildValue("(i)", is_training));
  CHECK_PY(r);
  if (prev) *prev = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

int MXAutogradIsRecording(bool *curr) {
  API_BEGIN();
  PyObject *r = CallV("autograd_is_recording", PyTuple_New(0));
  CHECK_PY(r);
  *curr = PyLong_AsLong(r) != 0;
  Py_DECREF(r);
  return 0;
}

int MXAutogradIsTraining(bool *curr) {
  API_BEGIN();
  PyObject *r = CallV("autograd_is_training", PyTuple_New(0));
  CHECK_PY(r);
  *curr = PyLong_AsLong(r) != 0;
  Py_DECREF(r);
  return 0;
}

int MXAutogradMarkVariables(mx_uint num_var, NDArrayHandle *var_handles,
                            mx_uint *reqs_array, NDArrayHandle *grad_handles) {
  API_BEGIN();
  PyObject *vars = HandleList((int)num_var, var_handles);
  PyObject *reqs = PyList_New(num_var);
  for (mx_uint i = 0; i < num_var; ++i)
    PyList_SET_ITEM(reqs, i, PyLong_FromUnsignedLong(reqs_array[i]));
  PyObject *grads = HandleList((int)num_var, grad_handles);
  PyObject *r = CallV("autograd_mark_variables",
                      Py_BuildValue("(NNN)", vars, reqs, grads));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXAutogradBackwardEx(mx_uint num_output, NDArrayHandle *output_handles,
                         NDArrayHandle *ograd_handles, int retain_graph,
                         int train_mode) {
  API_BEGIN();
  PyObject *outs = HandleList((int)num_output, output_handles);
  PyObject *ogs = ograd_handles
                      ? HandleList((int)num_output, ograd_handles)
                      : PyList_New(0);
  PyObject *r = CallV("autograd_backward",
                      Py_BuildValue("(NNii)", outs, ogs, retain_graph, train_mode));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXAutogradBackward(mx_uint num_output, NDArrayHandle *output_handles,
                       NDArrayHandle *ograd_handles, int retain_graph) {
  return MXAutogradBackwardEx(num_output, output_handles, ograd_handles,
                              retain_graph, 1);
}

/* --------------------------------------------------------- cachedop -- */

int MXCreateCachedOp(SymbolHandle handle, CachedOpHandle *out) {
  API_BEGIN();
  PyObject *r = CallV("cached_op_create", Py_BuildValue("(O)", (PyObject *)handle));
  CHECK_PY(r);
  *out = (CachedOpHandle)r;
  return 0;
}

int MXFreeCachedOp(CachedOpHandle handle) {
  if (handle) { Gil g; Py_DECREF((PyObject *)handle); }
  return 0;
}

int MXInvokeCachedOp(CachedOpHandle handle, int num_inputs,
                     NDArrayHandle *inputs, int *num_outputs,
                     NDArrayHandle **outputs) {
  API_BEGIN();
  PyObject *ins = HandleList(num_inputs, inputs);
  PyObject *r = CallV("cached_op_invoke",
                      Py_BuildValue("(ON)", (PyObject *)handle, ins));
  CHECK_PY(r);
  mx_uint n = 0;
  *outputs = (NDArrayHandle *)StoreHandleList(r, &n);
  *num_outputs = (int)n;
  Py_DECREF(r);
  return 0;
}

/* ----------------------------------------------------------- symbol -- */

int MXSymbolCreateAtomicSymbol(AtomicSymbolCreator creator, mx_uint num_param,
                               const char **keys, const char **vals,
                               SymbolHandle *out) {
  API_BEGIN();
  PyObject *kl = StrList((int)num_param, keys);
  PyObject *vl = StrList((int)num_param, vals);
  PyObject *r = CallV("symbol_create_atomic",
                      Py_BuildValue("(sNN)", CreatorName(creator), kl, vl));
  CHECK_PY(r);
  *out = (SymbolHandle)r;
  return 0;
}

int MXSymbolCreateVariable(const char *name, SymbolHandle *out) {
  API_BEGIN();
  PyObject *r = CallV("symbol_create_variable", Py_BuildValue("(s)", name));
  CHECK_PY(r);
  *out = (SymbolHandle)r;
  return 0;
}

int MXSymbolCreateGroup(mx_uint num_symbols, SymbolHandle *symbols,
                        SymbolHandle *out) {
  API_BEGIN();
  PyObject *l = HandleList((int)num_symbols, symbols);
  PyObject *r = CallV("symbol_create_group", Py_BuildValue("(N)", l));
  CHECK_PY(r);
  *out = (SymbolHandle)r;
  return 0;
}

int MXSymbolCreateFromFile(const char *fname, SymbolHandle *out) {
  API_BEGIN();
  PyObject *r = CallV("symbol_from_file", Py_BuildValue("(s)", fname));
  CHECK_PY(r);
  *out = (SymbolHandle)r;
  return 0;
}

int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out) {
  API_BEGIN();
  PyObject *r = CallV("symbol_from_json", Py_BuildValue("(s)", json));
  CHECK_PY(r);
  *out = (SymbolHandle)r;
  return 0;
}

int MXSymbolSaveToFile(SymbolHandle symbol, const char *fname) {
  API_BEGIN();
  PyObject *r = CallV("symbol_save_file",
                      Py_BuildValue("(Os)", (PyObject *)symbol, fname));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

static int StrGetter(const char *fn, void *handle, const char **out) {
  PyObject *r = CallV(fn, Py_BuildValue("(O)", (PyObject *)handle));
  if (r == nullptr) return Fail();
  ret.blob = PyUnicode_Check(r) ? PyUnicode_AsUTF8(r) : "";
  Py_DECREF(r);
  *out = ret.blob.c_str();
  return 0;
}

int MXSymbolSaveToJSON(SymbolHandle symbol, const char **out_json) {
  API_BEGIN();
  return StrGetter("symbol_to_json", symbol, out_json);
}

int MXSymbolFree(SymbolHandle symbol) {
  if (symbol == nullptr) return 0;
  API_BEGIN();
  PyObject *r = CallV("symbol_free", Py_BuildValue("(O)", (PyObject *)symbol));
  Py_XDECREF(r);
  if (r == nullptr) PyErr_Clear();
  Py_DECREF((PyObject *)symbol);
  return 0;
}

int MXSymbolCopy(SymbolHandle symbol, SymbolHandle *out) {
  API_BEGIN();
  PyObject *r = CallV("symbol_copy", Py_BuildValue("(O)", (PyObject *)symbol));
  CHECK_PY(r);
  *out = (SymbolHandle)r;
  return 0;
}

int MXSymbolPrint(SymbolHandle symbol, const char **out_str) {
  API_BEGIN();
  return StrGetter("symbol_print", symbol, out_str);
}

int MXSymbolGetName(SymbolHandle symbol, const char **out, int *success) {
  API_BEGIN();
  if (StrGetter("symbol_get_name", symbol, out) != 0) return -1;
  *success = (**out != '\0');
  return 0;
}

int MXSymbolGetAttr(SymbolHandle symbol, const char *key, const char **out,
                    int *success) {
  API_BEGIN();
  PyObject *r = CallV("symbol_get_attr",
                      Py_BuildValue("(Os)", (PyObject *)symbol, key));
  CHECK_PY(r);
  if (r == Py_None) {
    *success = 0; *out = nullptr;
  } else {
    ret.blob = PyUnicode_AsUTF8(r);
    *out = ret.blob.c_str();
    *success = 1;
  }
  Py_DECREF(r);
  return 0;
}

int MXSymbolSetAttr(SymbolHandle symbol, const char *key, const char *value) {
  API_BEGIN();
  PyObject *r = CallV("symbol_set_attr",
                      Py_BuildValue("(Oss)", (PyObject *)symbol, key, value));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

static int StrListGetter(const char *fn, void *handle, mx_uint *out_size,
                         const char ***out) {
  PyObject *r = CallV(fn, Py_BuildValue("(O)", (PyObject *)handle));
  if (r == nullptr) return Fail();
  *out = StoreStrList(r, out_size);
  Py_DECREF(r);
  return 0;
}

int MXSymbolListAttr(SymbolHandle symbol, mx_uint *out_size,
                     const char ***out) {
  API_BEGIN();
  int rc = StrListGetter("symbol_list_attr", symbol, out_size, out);
  /* reference ABI: out_size counts key/value PAIRS; out holds 2*out_size
     strings (c_api_symbolic.cc:297) */
  if (rc == 0) *out_size /= 2;
  return rc;
}

int MXSymbolListArguments(SymbolHandle symbol, mx_uint *out_size,
                          const char ***out_str_array) {
  API_BEGIN();
  return StrListGetter("symbol_list_arguments", symbol, out_size, out_str_array);
}

int MXSymbolListOutputs(SymbolHandle symbol, mx_uint *out_size,
                        const char ***out_str_array) {
  API_BEGIN();
  return StrListGetter("symbol_list_outputs", symbol, out_size, out_str_array);
}

int MXSymbolListAuxiliaryStates(SymbolHandle symbol, mx_uint *out_size,
                                const char ***out_str_array) {
  API_BEGIN();
  return StrListGetter("symbol_list_aux", symbol, out_size, out_str_array);
}

int MXSymbolGetInternals(SymbolHandle symbol, SymbolHandle *out) {
  API_BEGIN();
  PyObject *r = CallV("symbol_get_internals", Py_BuildValue("(O)", (PyObject *)symbol));
  CHECK_PY(r);
  *out = (SymbolHandle)r;
  return 0;
}

int MXSymbolGetChildren(SymbolHandle symbol, SymbolHandle *out) {
  API_BEGIN();
  PyObject *r = CallV("symbol_get_children", Py_BuildValue("(O)", (PyObject *)symbol));
  CHECK_PY(r);
  *out = (SymbolHandle)r;
  return 0;
}

int MXSymbolGetOutput(SymbolHandle symbol, mx_uint index, SymbolHandle *out) {
  API_BEGIN();
  PyObject *r = CallV("symbol_get_output",
                      Py_BuildValue("(OI)", (PyObject *)symbol, index));
  CHECK_PY(r);
  *out = (SymbolHandle)r;
  return 0;
}

int MXSymbolCompose(SymbolHandle sym, const char *name, mx_uint num_args,
                    const char **keys, SymbolHandle *args) {
  API_BEGIN();
  /* The reference mutates the nnvm symbol in place (compose returns
   * void and the caller keeps using `sym`). Our Symbol is immutable, so
   * the bridge records handle→composed in a side table consulted by
   * every other symbol_* helper (purged by MXSymbolFree). */
  PyObject *kl = keys ? StrList((int)num_args, keys) : PyList_New(0);
  PyObject *al = HandleList((int)num_args, args);
  PyObject *r = CallV("symbol_compose_inplace",
                      Py_BuildValue("(OsNN)", (PyObject *)sym,
                                    name ? name : "", kl, al));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXSymbolGrad(SymbolHandle sym, mx_uint num_wrt, const char **wrt,
                 SymbolHandle *out) {
  API_BEGIN();
  PyObject *wl = StrList((int)num_wrt, wrt);
  PyObject *r = CallV("symbol_grad", Py_BuildValue("(ON)", (PyObject *)sym, wl));
  CHECK_PY(r);
  *out = (SymbolHandle)r;
  return 0;
}

static int InferShapeImpl(SymbolHandle sym, mx_uint num_args, const char **keys,
                          const mx_uint *arg_ind_ptr,
                          const mx_uint *arg_shape_data, int which_partial,
                          mx_uint *sizes[3], const mx_uint **ndims[3],
                          const mx_uint ***datas[3], int *complete) {
  PyObject *kl = StrList((int)num_args, keys);
  PyObject *ind = UIntList((int)num_args + 1, arg_ind_ptr);
  mx_uint total = num_args ? arg_ind_ptr[num_args] : 0;
  PyObject *dat = UIntList((int)total, arg_shape_data);
  PyObject *r = CallV("symbol_infer_shape",
                      Py_BuildValue("(ONNNi)", (PyObject *)sym, kl, ind, dat,
                                    which_partial));
  if (r == nullptr) return Fail();
  for (int part = 0; part < 3; ++part) {
    PyObject *shapes = PyTuple_GET_ITEM(r, part);
    Py_ssize_t n = PySequence_Size(shapes);
    ret.ndims[part].clear();
    ret.dims[part].assign((size_t)n, {});
    ret.dptr[part].clear();
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject *s = PySequence_GetItem(shapes, i);
      Py_ssize_t d = PySequence_Size(s);
      ret.ndims[part].push_back((mx_uint)d);
      for (Py_ssize_t j = 0; j < d; ++j) {
        PyObject *x = PySequence_GetItem(s, j);
        ret.dims[part][i].push_back((mx_uint)PyLong_AsUnsignedLong(x));
        Py_DECREF(x);
      }
      Py_DECREF(s);
    }
    for (auto &v : ret.dims[part]) ret.dptr[part].push_back(v.data());
    *sizes[part] = (mx_uint)n;
    *ndims[part] = ret.ndims[part].data();
    *datas[part] = ret.dptr[part].data();
  }
  Py_DECREF(r);
  *complete = 1;
  return 0;
}

int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args, const char **keys,
                       const mx_uint *arg_ind_ptr, const mx_uint *arg_shape_data,
                       mx_uint *in_shape_size, const mx_uint **in_shape_ndim,
                       const mx_uint ***in_shape_data, mx_uint *out_shape_size,
                       const mx_uint **out_shape_ndim,
                       const mx_uint ***out_shape_data, mx_uint *aux_shape_size,
                       const mx_uint **aux_shape_ndim,
                       const mx_uint ***aux_shape_data, int *complete) {
  API_BEGIN();
  mx_uint *sizes[3] = {in_shape_size, out_shape_size, aux_shape_size};
  const mx_uint **ndims[3] = {in_shape_ndim, out_shape_ndim, aux_shape_ndim};
  const mx_uint ***datas[3] = {in_shape_data, out_shape_data, aux_shape_data};
  return InferShapeImpl(sym, num_args, keys, arg_ind_ptr, arg_shape_data, 0,
                        sizes, ndims, datas, complete);
}

int MXSymbolInferShapePartial(SymbolHandle sym, mx_uint num_args,
                              const char **keys, const mx_uint *arg_ind_ptr,
                              const mx_uint *arg_shape_data,
                              mx_uint *in_shape_size,
                              const mx_uint **in_shape_ndim,
                              const mx_uint ***in_shape_data,
                              mx_uint *out_shape_size,
                              const mx_uint **out_shape_ndim,
                              const mx_uint ***out_shape_data,
                              mx_uint *aux_shape_size,
                              const mx_uint **aux_shape_ndim,
                              const mx_uint ***aux_shape_data, int *complete) {
  API_BEGIN();
  mx_uint *sizes[3] = {in_shape_size, out_shape_size, aux_shape_size};
  const mx_uint **ndims[3] = {in_shape_ndim, out_shape_ndim, aux_shape_ndim};
  const mx_uint ***datas[3] = {in_shape_data, out_shape_data, aux_shape_data};
  return InferShapeImpl(sym, num_args, keys, arg_ind_ptr, arg_shape_data, 1,
                        sizes, ndims, datas, complete);
}

int MXSymbolInferType(SymbolHandle sym, mx_uint num_args, const char **keys,
                      const int *arg_type_data, mx_uint *in_type_size,
                      const int **in_type_data, mx_uint *out_type_size,
                      const int **out_type_data, mx_uint *aux_type_size,
                      const int **aux_type_data, int *complete) {
  API_BEGIN();
  PyObject *kl = StrList((int)num_args, keys);
  PyObject *tl = IntList((int)num_args, arg_type_data);
  PyObject *r = CallV("symbol_infer_type",
                      Py_BuildValue("(ONN)", (PyObject *)sym, kl, tl));
  CHECK_PY(r);
  mx_uint *sizes[3] = {in_type_size, out_type_size, aux_type_size};
  const int **datas[3] = {in_type_data, out_type_data, aux_type_data};
  for (int part = 0; part < 3; ++part) {
    PyObject *ts = PyTuple_GET_ITEM(r, part);
    Py_ssize_t n = PySequence_Size(ts);
    ret.types[part].clear();
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject *x = PySequence_GetItem(ts, i);
      ret.types[part].push_back((int)PyLong_AsLong(x));
      Py_DECREF(x);
    }
    *sizes[part] = (mx_uint)n;
    *datas[part] = ret.types[part].data();
  }
  Py_DECREF(r);
  *complete = 1;
  return 0;
}

/* --------------------------------------------------------- executor -- */

int MXExecutorFree(ExecutorHandle handle) {
  if (handle) { Gil g; Py_DECREF((PyObject *)handle); }
  return 0;
}

int MXExecutorPrint(ExecutorHandle handle, const char **out_str) {
  API_BEGIN();
  return StrGetter("executor_print", handle, out_str);
}

int MXExecutorForward(ExecutorHandle handle, int is_train) {
  API_BEGIN();
  PyObject *r = CallV("executor_forward",
                      Py_BuildValue("(Oi)", (PyObject *)handle, is_train));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXExecutorBackward(ExecutorHandle handle, mx_uint len,
                       NDArrayHandle *head_grads) {
  API_BEGIN();
  PyObject *hl = HandleList((int)len, head_grads);
  PyObject *r = CallV("executor_backward",
                      Py_BuildValue("(ON)", (PyObject *)handle, hl));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXExecutorOutputs(ExecutorHandle handle, mx_uint *out_size,
                      NDArrayHandle **out) {
  API_BEGIN();
  PyObject *r = CallV("executor_outputs", Py_BuildValue("(O)", (PyObject *)handle));
  CHECK_PY(r);
  *out = (NDArrayHandle *)StoreHandleList(r, out_size);
  Py_DECREF(r);
  return 0;
}

int MXExecutorBind(SymbolHandle symbol_handle, int dev_type, int dev_id,
                   mx_uint len, NDArrayHandle *in_args,
                   NDArrayHandle *arg_grad_store, mx_uint *grad_req_type,
                   mx_uint aux_states_len, NDArrayHandle *aux_states,
                   ExecutorHandle *out) {
  API_BEGIN();
  PyObject *args = HandleList((int)len, in_args);
  PyObject *grads = HandleList((int)len, arg_grad_store);
  PyObject *reqs = PyList_New(len);
  for (mx_uint i = 0; i < len; ++i)
    PyList_SET_ITEM(reqs, i,
                    PyLong_FromUnsignedLong(grad_req_type ? grad_req_type[i] : 1));
  PyObject *aux = HandleList((int)aux_states_len, aux_states);
  PyObject *r = CallV("executor_bind",
                      Py_BuildValue("(OiiNNNN)", (PyObject *)symbol_handle,
                                    dev_type, dev_id, args, grads, reqs, aux));
  CHECK_PY(r);
  *out = (ExecutorHandle)r;
  return 0;
}

/* ---------------------------------------------------------- data io -- */

static std::vector<std::string> *iter_names = nullptr;

static int EnsureIterNames() {
  if (iter_names) return 0;
  PyObject *r = CallV("list_data_iters", PyTuple_New(0));
  if (r == nullptr) return Fail();
  auto *names = new std::vector<std::string>();
  Py_ssize_t n = PySequence_Size(r);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *it = PySequence_GetItem(r, i);
    names->push_back(PyUnicode_AsUTF8(it));
    Py_DECREF(it);
  }
  Py_DECREF(r);
  iter_names = names;
  return 0;
}

int MXListDataIters(mx_uint *out_size, DataIterHandle **out_array) {
  API_BEGIN();
  if (EnsureIterNames() != 0) return -1;
  ret.handles.clear();
  for (auto &s : *iter_names) ret.handles.push_back((void *)&s);
  *out_size = (mx_uint)iter_names->size();
  *out_array = ret.handles.data();
  return 0;
}

int MXDataIterGetIterInfo(DataIterHandle creator, const char **name,
                          const char **description, mx_uint *num_args,
                          const char ***arg_names,
                          const char ***arg_type_infos,
                          const char ***arg_descriptions) {
  *name = ((const std::string *)creator)->c_str();
  *description = "";
  *num_args = 0;
  static const char *empty = nullptr;
  *arg_names = &empty;
  *arg_type_infos = &empty;
  *arg_descriptions = &empty;
  return 0;
}

int MXDataIterCreateIter(DataIterHandle creator, mx_uint num_param,
                         const char **keys, const char **vals,
                         DataIterHandle *out) {
  API_BEGIN();
  PyObject *kl = StrList((int)num_param, keys);
  PyObject *vl = StrList((int)num_param, vals);
  PyObject *it = CallV("data_iter_create",
                       Py_BuildValue("(sNN)",
                                     ((const std::string *)creator)->c_str(),
                                     kl, vl));
  CHECK_PY(it);
  PyObject *st = CallV("iter_state_new", Py_BuildValue("(N)", it));
  CHECK_PY(st);
  *out = (DataIterHandle)st;
  return 0;
}

int MXDataIterFree(DataIterHandle handle) {
  if (handle) { Gil g; Py_DECREF((PyObject *)handle); }
  return 0;
}

int MXDataIterNext(DataIterHandle handle, int *out) {
  return IntGetter("data_iter_next", handle, out);
}

int MXDataIterBeforeFirst(DataIterHandle handle) {
  API_BEGIN();
  PyObject *r = CallV("data_iter_before_first", Py_BuildValue("(O)", (PyObject *)handle));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXDataIterGetData(DataIterHandle handle, NDArrayHandle *out) {
  return UnaryHandleOp("data_iter_get_data", handle, out);
}

int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle *out) {
  return UnaryHandleOp("data_iter_get_label", handle, out);
}

int MXDataIterGetPadNum(DataIterHandle handle, int *pad) {
  return IntGetter("data_iter_get_pad", handle, pad);
}

/* ---------------------------------------------------------- kvstore -- */

int MXKVStoreCreate(const char *type, KVStoreHandle *out) {
  API_BEGIN();
  PyObject *r = CallV("kv_create", Py_BuildValue("(s)", type));
  CHECK_PY(r);
  *out = (KVStoreHandle)r;
  return 0;
}

int MXKVStoreFree(KVStoreHandle handle) {
  if (handle) { Gil g; Py_DECREF((PyObject *)handle); }
  return 0;
}

static int KVKeysVals(const char *fn, KVStoreHandle handle, mx_uint num,
                      const int *keys, NDArrayHandle *vals, int priority) {
  PyObject *kl = IntList((int)num, keys);
  PyObject *vl = HandleList((int)num, vals);
  PyObject *r = CallV(fn, Py_BuildValue("(ONNi)", (PyObject *)handle, kl, vl,
                                        priority));
  if (r == nullptr) return Fail();
  Py_DECREF(r);
  return 0;
}

int MXKVStoreInit(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals) {
  API_BEGIN();
  PyObject *kl = IntList((int)num, keys);
  PyObject *vl = HandleList((int)num, vals);
  PyObject *r = CallV("kv_init", Py_BuildValue("(ONN)", (PyObject *)handle, kl, vl));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXKVStorePush(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals, int priority) {
  API_BEGIN();
  return KVKeysVals("kv_push", handle, num, keys, vals, priority);
}

int MXKVStorePull(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals, int priority) {
  API_BEGIN();
  return KVKeysVals("kv_pull", handle, num, keys, vals, priority);
}

int MXKVStoreGetType(KVStoreHandle handle, const char **type) {
  API_BEGIN();
  return StrGetter("kv_type", handle, type);
}

int MXKVStoreGetRank(KVStoreHandle handle, int *ret_) {
  return IntGetter("kv_rank", handle, ret_);
}

int MXKVStoreGetGroupSize(KVStoreHandle handle, int *ret_) {
  return IntGetter("kv_group_size", handle, ret_);
}

static int RoleIs(const char *role, int *ret_) {
  const char *r = getenv("DMLC_ROLE");
  *ret_ = (r != nullptr && std::strcmp(r, role) == 0) ? 1 : 0;
  return 0;
}

int MXKVStoreIsWorkerNode(int *ret_) {
  const char *r = getenv("DMLC_ROLE");
  *ret_ = (r == nullptr || std::strcmp(r, "worker") == 0) ? 1 : 0;
  return 0;
}

int MXKVStoreIsServerNode(int *ret_) { return RoleIs("server", ret_); }

int MXKVStoreIsSchedulerNode(int *ret_) { return RoleIs("scheduler", ret_); }

int MXKVStoreBarrier(KVStoreHandle handle) {
  API_BEGIN();
  PyObject *r = CallV("kv_barrier", Py_BuildValue("(O)", (PyObject *)handle));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXKVStoreGetNumDeadNode(KVStoreHandle handle, int node_id, int *number) {
  API_BEGIN();
  PyObject *r = CallV("kv_num_dead_node",
                      Py_BuildValue("(Oi)", (PyObject *)handle, node_id));
  CHECK_PY(r);
  *number = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

int MXKVStoreRunServer(KVStoreHandle handle) {
  API_BEGIN();
  PyObject *r = CallV("kv_run_server", Py_BuildValue("(O)", (PyObject *)handle));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXKVStoreSendCommmandToServers(KVStoreHandle handle, int cmd_id,
                                   const char *cmd_body) {
  API_BEGIN();
  PyObject *r = CallV("kv_send_command",
                      Py_BuildValue("(Ois)", (PyObject *)handle, cmd_id,
                                    cmd_body));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

/* --------------------------------------------------------- recordio -- */
/* Pure native path — delegates to the runtime library (src/recordio.cc),
 * no interpreter involved. */

int MXRecordIOWriterCreate(const char *uri, RecordIOHandle *out) {
  return MXTRecordIOWriterCreate(uri, out);
}

int MXRecordIOWriterFree(RecordIOHandle handle) {
  return MXTRecordIOWriterFree(handle);
}

int MXRecordIOWriterWriteRecord(RecordIOHandle handle, const char *buf,
                                size_t size) {
  return MXTRecordIOWriterWrite(handle, buf, size);
}

int MXRecordIOWriterTell(RecordIOHandle handle, size_t *pos) {
  return MXTRecordIOWriterTell(handle, pos);
}

int MXRecordIOReaderCreate(const char *uri, RecordIOHandle *out) {
  return MXTRecordIOReaderCreate(uri, out);
}

int MXRecordIOReaderFree(RecordIOHandle handle) {
  return MXTRecordIOReaderFree(handle);
}

int MXRecordIOReaderReadRecord(RecordIOHandle handle, const char **buf,
                               size_t *size) {
  return MXTRecordIOReaderNext(handle, buf, size);
}

int MXRecordIOReaderSeek(RecordIOHandle handle, size_t pos) {
  return MXTRecordIOReaderSeek(handle, pos);
}


/* ------------------------------------------------------------------------
 * Round-3 additions: remaining reference entry points (146/146 parity).
 * Reference: include/mxnet/c_api.h; bridge helpers in _c_api_impl.py.
 * ---------------------------------------------------------------------- */

int MXImperativeInvokeEx(AtomicSymbolCreator creator, int num_inputs,
                         NDArrayHandle *inputs, int *num_outputs,
                         NDArrayHandle **outputs, int num_params,
                         const char **param_keys, const char **param_vals,
                         const int **out_stypes) {
  API_BEGIN();
  PyObject *ins = HandleList(num_inputs, inputs);
  PyObject *keys = StrList(num_params, param_keys);
  PyObject *vals = StrList(num_params, param_vals);
  int n_provided = (*num_outputs > 0 && *outputs != nullptr) ? *num_outputs : 0;
  PyObject *outs = HandleList(n_provided, (void **)(n_provided ? *outputs : nullptr));
  PyObject *r = CallV("imperative_invoke_ex",
                      Py_BuildValue("(sNNNiN)", CreatorName(creator), ins,
                                    keys, vals, n_provided, outs));
  CHECK_PY(r);
  PyObject *arrs = PyTuple_GetItem(r, 0);
  PyObject *stypes = PyTuple_GetItem(r, 1);
  mx_uint n = 0;
  if (n_provided == 0) {
    *outputs = (NDArrayHandle *)StoreHandleList(arrs, &n);
    *num_outputs = (int)n;
  } else {
    *num_outputs = (int)PySequence_Size(arrs);
  }
  ret.ints.clear();
  for (Py_ssize_t i = 0; i < PySequence_Size(stypes); ++i) {
    PyObject *it = PySequence_GetItem(stypes, i);
    ret.ints.push_back((int)PyLong_AsLong(it));
    Py_DECREF(it);
  }
  *out_stypes = ret.ints.data();
  Py_DECREF(r);
  return 0;
}

int MXInvokeCachedOpEx(CachedOpHandle handle, int num_inputs,
                       NDArrayHandle *inputs, int *num_outputs,
                       NDArrayHandle **outputs, const int **out_stypes) {
  API_BEGIN();
  PyObject *ins = HandleList(num_inputs, inputs);
  PyObject *r = CallV("cached_op_invoke_ex",
                      Py_BuildValue("(ON)", (PyObject *)handle, ins));
  CHECK_PY(r);
  PyObject *arrs = PyTuple_GetItem(r, 0);
  PyObject *stypes = PyTuple_GetItem(r, 1);
  mx_uint n = 0;
  *outputs = (NDArrayHandle *)StoreHandleList(arrs, &n);
  *num_outputs = (int)n;
  ret.ints.clear();
  for (Py_ssize_t i = 0; i < PySequence_Size(stypes); ++i) {
    PyObject *it = PySequence_GetItem(stypes, i);
    ret.ints.push_back((int)PyLong_AsLong(it));
    Py_DECREF(it);
  }
  *out_stypes = ret.ints.data();
  Py_DECREF(r);
  return 0;
}

/* -- sparse containers -- */

int MXNDArrayCreateSparseEx(int storage_type, const mx_uint *shape,
                            mx_uint ndim, int dev_type, int dev_id,
                            int delay_alloc, int dtype, mx_uint num_aux,
                            int *aux_type, mx_uint *aux_ndims,
                            const mx_uint *aux_shape, NDArrayHandle *out) {
  (void)delay_alloc;
  API_BEGIN();
  PyObject *shp = UIntList((int)ndim, shape);
  PyObject *atypes = IntList((int)num_aux, aux_type);
  PyObject *ashapes = PyList_New(num_aux);
  mx_uint off = 0;
  for (mx_uint i = 0; i < num_aux; ++i) {
    PyObject *one = UIntList((int)aux_ndims[i], aux_shape + off);
    off += aux_ndims[i];
    PyList_SET_ITEM(ashapes, i, one);
  }
  PyObject *r = CallV("nd_create_sparse",
                      Py_BuildValue("(iNiiiNN)", storage_type, shp, dev_type,
                                    dev_id, dtype, atypes, ashapes));
  CHECK_PY(r);
  *out = (NDArrayHandle)r;
  return 0;
}

int MXNDArrayGetAuxType(NDArrayHandle handle, mx_uint i, int *out_type) {
  API_BEGIN();
  PyObject *r = CallV("nd_aux_type",
                      Py_BuildValue("(Oi)", (PyObject *)handle, (int)i));
  CHECK_PY(r);
  *out_type = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

int MXNDArrayGetAuxNDArray(NDArrayHandle handle, mx_uint i,
                           NDArrayHandle *out) {
  API_BEGIN();
  PyObject *r = CallV("nd_get_aux",
                      Py_BuildValue("(Oi)", (PyObject *)handle, (int)i));
  CHECK_PY(r);
  *out = (NDArrayHandle)r;
  return 0;
}

int MXNDArrayGetDataNDArray(NDArrayHandle handle, NDArrayHandle *out) {
  API_BEGIN();
  PyObject *r = CallV("nd_get_data", Py_BuildValue("(O)", (PyObject *)handle));
  CHECK_PY(r);
  *out = (NDArrayHandle)r;
  return 0;
}

int MXNDArrayGetGradState(NDArrayHandle handle, int *out) {
  API_BEGIN();
  PyObject *r = CallV("nd_grad_state", Py_BuildValue("(O)", (PyObject *)handle));
  CHECK_PY(r);
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

int MXNDArraySetGradState(NDArrayHandle handle, int state) {
  API_BEGIN();
  PyObject *r = CallV("nd_set_grad_state",
                      Py_BuildValue("(Oi)", (PyObject *)handle, state));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXNDArraySyncCopyFromNDArray(NDArrayHandle handle_dst,
                                 const NDArrayHandle handle_src, const int i) {
  API_BEGIN();
  PyObject *r = CallV("nd_sync_copy_from_ndarray",
                      Py_BuildValue("(OOi)", (PyObject *)handle_dst,
                                    (PyObject *)handle_src, i));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

/* -- autograd extras -- */

int MXAutogradComputeGradient(mx_uint num_output,
                              NDArrayHandle *output_handles) {
  return MXAutogradBackward(num_output, output_handles, nullptr, 0);
}

int MXAutogradGetSymbol(NDArrayHandle handle, SymbolHandle *out) {
  API_BEGIN();
  PyObject *r = CallV("autograd_get_symbol",
                      Py_BuildValue("(O)", (PyObject *)handle));
  CHECK_PY(r);
  *out = (SymbolHandle)r;
  return 0;
}

int MXCustomFunctionRecord(int num_inputs, NDArrayHandle *inputs,
                           int num_outputs, NDArrayHandle *outputs,
                           struct MXCallbackList *callbacks) {
  API_BEGIN();
  PyObject *ins = HandleList(num_inputs, inputs);
  PyObject *outs = HandleList(num_outputs, outputs);
  PyObject *cbs = PyList_New(callbacks->num_callbacks);
  for (int i = 0; i < callbacks->num_callbacks; ++i) {
    PyObject *pair = Py_BuildValue("(KK)",
        (unsigned long long)(uintptr_t)callbacks->callbacks[i],
        (unsigned long long)(uintptr_t)callbacks->contexts[i]);
    PyList_SET_ITEM(cbs, i, pair);
  }
  PyObject *r = CallV("custom_function_record",
                      Py_BuildValue("(NNN)", ins, outs, cbs));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXCustomOpRegister(const char *op_type, CustomOpPropCreator creator) {
  API_BEGIN();
  PyObject *r = CallV("custom_op_register",
                      Py_BuildValue("(sK)", op_type,
                                    (unsigned long long)(uintptr_t)creator));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

/* -- legacy NDArray-function registry -- */

int MXListFunctions(mx_uint *out_size, FunctionHandle **out_array) {
  API_BEGIN();
  PyObject *r = CallV("list_functions", PyTuple_New(0));
  CHECK_PY(r);
  *out_array = (FunctionHandle *)StoreHandleList(r, out_size);
  Py_DECREF(r);
  return 0;
}

int MXGetFunction(const char *name, FunctionHandle *out) {
  API_BEGIN();
  PyObject *r = CallV("get_function", Py_BuildValue("(s)", name));
  CHECK_PY(r);
  *out = (FunctionHandle)r;
  return 0;
}

int MXFuncDescribe(FunctionHandle fun, mx_uint *num_use_vars,
                   mx_uint *num_scalars, mx_uint *num_mutate_vars,
                   int *type_mask) {
  API_BEGIN();
  PyObject *r = CallV("func_describe", Py_BuildValue("(O)", (PyObject *)fun));
  CHECK_PY(r);
  *num_use_vars = (mx_uint)PyLong_AsLong(PyTuple_GetItem(r, 0));
  *num_scalars = (mx_uint)PyLong_AsLong(PyTuple_GetItem(r, 1));
  *num_mutate_vars = (mx_uint)PyLong_AsLong(PyTuple_GetItem(r, 2));
  *type_mask = (int)PyLong_AsLong(PyTuple_GetItem(r, 3));
  Py_DECREF(r);
  return 0;
}

int MXFuncGetInfo(FunctionHandle fun, const char **name,
                  const char **description, mx_uint *num_args,
                  const char ***arg_names, const char ***arg_type_infos,
                  const char ***arg_descriptions, const char **return_type) {
  API_BEGIN();
  PyObject *r = CallV("func_get_info", Py_BuildValue("(O)", (PyObject *)fun));
  CHECK_PY(r);
  /* storage layout mirrors MXSymbolGetAtomicSymbolInfo: strings go into
     thread-local ret. */
  ret.strings.clear();
  auto keep = [&](PyObject *o) {
    ret.strings.emplace_back(PyUnicode_Check(o) ? PyUnicode_AsUTF8(o) : "");
  };
  keep(PyTuple_GetItem(r, 0));
  keep(PyTuple_GetItem(r, 1));
  PyObject *args = PyTuple_GetItem(r, 2);
  PyObject *tinfos = PyTuple_GetItem(r, 3);
  PyObject *descs = PyTuple_GetItem(r, 4);
  keep(PyTuple_GetItem(r, 5));
  Py_ssize_t n = PySequence_Size(args);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *a = PySequence_GetItem(args, i); keep(a); Py_DECREF(a);
    PyObject *t = PySequence_GetItem(tinfos, i); keep(t); Py_DECREF(t);
    PyObject *d = PySequence_GetItem(descs, i); keep(d); Py_DECREF(d);
  }
  ret.cptrs.clear();
  for (auto &s : ret.strings) ret.cptrs.push_back(s.c_str());
  *name = ret.cptrs[0];
  *description = ret.cptrs[1];
  if (return_type) *return_type = ret.cptrs[2];
  *num_args = (mx_uint)n;
  /* triples start at index 3: name,i type,i desc,i interleaved */
  ret.handles.clear();  /* reuse as scratch for pointer arrays */
  static thread_local std::vector<const char *> anames, atypes, adescs;
  anames.clear(); atypes.clear(); adescs.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    anames.push_back(ret.cptrs[3 + 3 * i]);
    atypes.push_back(ret.cptrs[3 + 3 * i + 1]);
    adescs.push_back(ret.cptrs[3 + 3 * i + 2]);
  }
  *arg_names = anames.data();
  *arg_type_infos = atypes.data();
  *arg_descriptions = adescs.data();
  Py_DECREF(r);
  return 0;
}

int MXFuncInvokeEx(FunctionHandle fun, NDArrayHandle *use_vars,
                   mx_float *scalar_args, NDArrayHandle *mutate_vars,
                   int num_params, char **param_keys, char **param_vals) {
  API_BEGIN();
  mx_uint n_use = 0, n_scalar = 0, n_mut = 0; int mask = 0;
  {
    PyObject *d = CallV("func_describe", Py_BuildValue("(O)", (PyObject *)fun));
    CHECK_PY(d);
    n_use = (mx_uint)PyLong_AsLong(PyTuple_GetItem(d, 0));
    n_scalar = (mx_uint)PyLong_AsLong(PyTuple_GetItem(d, 1));
    n_mut = (mx_uint)PyLong_AsLong(PyTuple_GetItem(d, 2));
    mask = (int)PyLong_AsLong(PyTuple_GetItem(d, 3));
    (void)mask;
    Py_DECREF(d);
  }
  PyObject *uses = HandleList((int)n_use, use_vars);
  PyObject *scalars = PyList_New(n_scalar);
  for (mx_uint i = 0; i < n_scalar; ++i)
    PyList_SET_ITEM(scalars, i, PyFloat_FromDouble(scalar_args ? scalar_args[i] : 0));
  PyObject *muts = HandleList((int)n_mut, mutate_vars);
  PyObject *keys = StrList(num_params, (const char *const *)param_keys);
  PyObject *vals = StrList(num_params, (const char *const *)param_vals);
  PyObject *r = CallV("func_invoke",
                      Py_BuildValue("(ONNNNN)", (PyObject *)fun, uses, scalars,
                                    muts, keys, vals));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXFuncInvoke(FunctionHandle fun, NDArrayHandle *use_vars,
                 mx_float *scalar_args, NDArrayHandle *mutate_vars) {
  return MXFuncInvokeEx(fun, use_vars, scalar_args, mutate_vars, 0, nullptr,
                        nullptr);
}

/* -- kvstore extras -- */

int MXInitPSEnv(mx_uint num_vars, const char **keys, const char **vals) {
  API_BEGIN();
  PyObject *r = CallV("init_ps_env",
                      Py_BuildValue("(NN)", StrList((int)num_vars, keys),
                                    StrList((int)num_vars, vals)));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXKVStoreInitEx(KVStoreHandle handle, mx_uint num, const char **keys,
                    NDArrayHandle *vals) {
  API_BEGIN();
  PyObject *r = CallV("kv_init_ex",
                      Py_BuildValue("(ONN)", (PyObject *)handle,
                                    StrList((int)num, keys),
                                    HandleList((int)num, vals)));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXKVStorePushEx(KVStoreHandle handle, mx_uint num, const char **keys,
                    NDArrayHandle *vals, int priority) {
  API_BEGIN();
  PyObject *r = CallV("kv_push_ex",
                      Py_BuildValue("(ONNi)", (PyObject *)handle,
                                    StrList((int)num, keys),
                                    HandleList((int)num, vals), priority));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXKVStorePullEx(KVStoreHandle handle, mx_uint num, const char **keys,
                    NDArrayHandle *vals, int priority) {
  API_BEGIN();
  PyObject *r = CallV("kv_pull_ex",
                      Py_BuildValue("(ONNi)", (PyObject *)handle,
                                    StrList((int)num, keys),
                                    HandleList((int)num, vals), priority));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXKVStorePullRowSparse(KVStoreHandle handle, mx_uint num,
                           const int *keys, NDArrayHandle *vals,
                           const NDArrayHandle *row_ids, int priority) {
  API_BEGIN();
  PyObject *r = CallV("kv_pull_row_sparse",
                      Py_BuildValue("(ONNNi)", (PyObject *)handle,
                                    IntList((int)num, keys),
                                    HandleList((int)num, vals),
                                    HandleList((int)num, (void *const *)row_ids),
                                    priority));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXKVStorePullRowSparseEx(KVStoreHandle handle, mx_uint num,
                             const char **keys, NDArrayHandle *vals,
                             const NDArrayHandle *row_ids, int priority) {
  API_BEGIN();
  PyObject *r = CallV("kv_pull_row_sparse",
                      Py_BuildValue("(ONNNi)", (PyObject *)handle,
                                    StrList((int)num, keys),
                                    HandleList((int)num, vals),
                                    HandleList((int)num, (void *const *)row_ids),
                                    priority));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXKVStoreSetBarrierBeforeExit(KVStoreHandle handle,
                                  const int barrier_before_exit) {
  API_BEGIN();
  PyObject *r = CallV("kv_set_barrier_before_exit",
                      Py_BuildValue("(Oi)", (PyObject *)handle,
                                    barrier_before_exit));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXKVStoreSetUpdaterEx(KVStoreHandle handle, MXKVStoreUpdater updater,
                          MXKVStoreStrUpdater str_updater,
                          void *updater_handle) {
  API_BEGIN();
  PyObject *r = CallV("kv_set_updater",
                      Py_BuildValue("(OKKK)", (PyObject *)handle,
                                    (unsigned long long)(uintptr_t)updater,
                                    (unsigned long long)(uintptr_t)str_updater,
                                    (unsigned long long)(uintptr_t)updater_handle));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXKVStoreSetUpdater(KVStoreHandle handle, MXKVStoreUpdater updater,
                        void *updater_handle) {
  return MXKVStoreSetUpdaterEx(handle, updater, nullptr, updater_handle);
}

/* -- executor extras -- */

int MXExecutorBackwardEx(ExecutorHandle handle, mx_uint len,
                         NDArrayHandle *head_grads, int is_train) {
  API_BEGIN();
  PyObject *grads = HandleList((int)len, head_grads);
  PyObject *r = CallV("executor_backward_ex",
                      Py_BuildValue("(ONi)", (PyObject *)handle, grads,
                                    is_train));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

static int BindXImpl(SymbolHandle symbol_handle, int dev_type, int dev_id,
                     mx_uint num_map_keys, const char **map_keys,
                     const int *map_dev_types, const int *map_dev_ids,
                     mx_uint len, NDArrayHandle *in_args,
                     NDArrayHandle *arg_grad_store, mx_uint *grad_req_type,
                     mx_uint aux_states_len, NDArrayHandle *aux_states,
                     ExecutorHandle *out) {
  API_BEGIN();
  PyObject *reqs = PyList_New(len);
  for (mx_uint i = 0; i < len; ++i)
    PyList_SET_ITEM(reqs, i,
                    PyLong_FromUnsignedLong(grad_req_type ? grad_req_type[i] : 1));
  PyObject *r = CallV(
      "executor_bind_x",
      Py_BuildValue("(OiiNNNNNNN)", (PyObject *)symbol_handle, dev_type,
                    dev_id, StrList((int)num_map_keys, map_keys),
                    IntList((int)num_map_keys, map_dev_types),
                    IntList((int)num_map_keys, map_dev_ids),
                    HandleList((int)len, in_args),
                    HandleList((int)len, arg_grad_store), reqs,
                    HandleList((int)aux_states_len, aux_states)));
  CHECK_PY(r);
  *out = (ExecutorHandle)r;
  return 0;
}

int MXExecutorBindX(SymbolHandle symbol_handle, int dev_type, int dev_id,
                    mx_uint num_map_keys, const char **map_keys,
                    const int *map_dev_types, const int *map_dev_ids,
                    mx_uint len, NDArrayHandle *in_args,
                    NDArrayHandle *arg_grad_store, mx_uint *grad_req_type,
                    mx_uint aux_states_len, NDArrayHandle *aux_states,
                    ExecutorHandle *out) {
  return BindXImpl(symbol_handle, dev_type, dev_id, num_map_keys, map_keys,
                   map_dev_types, map_dev_ids, len, in_args, arg_grad_store,
                   grad_req_type, aux_states_len, aux_states, out);
}

int MXExecutorBindEX(SymbolHandle symbol_handle, int dev_type, int dev_id,
                     mx_uint num_map_keys, const char **map_keys,
                     const int *map_dev_types, const int *map_dev_ids,
                     mx_uint len, NDArrayHandle *in_args,
                     NDArrayHandle *arg_grad_store, mx_uint *grad_req_type,
                     mx_uint aux_states_len, NDArrayHandle *aux_states,
                     ExecutorHandle shared_exec, ExecutorHandle *out) {
  (void)shared_exec;  /* memory sharing is XLA's concern here */
  return BindXImpl(symbol_handle, dev_type, dev_id, num_map_keys, map_keys,
                   map_dev_types, map_dev_ids, len, in_args, arg_grad_store,
                   grad_req_type, aux_states_len, aux_states, out);
}

int MXExecutorSimpleBind(
    SymbolHandle symbol_handle, int dev_type, int dev_id,
    const mx_uint num_g2c_keys, const char **g2c_keys,
    const int *g2c_dev_types, const int *g2c_dev_ids,
    const mx_uint provided_grad_req_list_len,
    const char **provided_grad_req_names,
    const char **provided_grad_req_types,
    const mx_uint num_provided_arg_shapes,
    const char **provided_arg_shape_names,
    const mx_uint *provided_arg_shape_data,
    const mx_uint *provided_arg_shape_idx,
    const mx_uint num_provided_arg_dtypes,
    const char **provided_arg_dtype_names, const int *provided_arg_dtypes,
    const mx_uint num_provided_arg_stypes,
    const char **provided_arg_stype_names, const int *provided_arg_stypes,
    const mx_uint num_shared_arg_names, const char **shared_arg_name_list,
    int *shared_buffer_len, const char **shared_buffer_name_list,
    NDArrayHandle *shared_buffer_handle_list,
    const char ***updated_shared_buffer_name_list,
    NDArrayHandle **updated_shared_buffer_handle_list,
    mx_uint *num_in_args, NDArrayHandle **in_args, NDArrayHandle **arg_grads,
    mx_uint *num_aux_states, NDArrayHandle **aux_states,
    ExecutorHandle shared_exec_handle, ExecutorHandle *out) {
  (void)num_shared_arg_names; (void)shared_arg_name_list;
  (void)shared_exec_handle;
  API_BEGIN();
  /* shapes arrive as a CSR pair (idx/data) keyed by name */
  PyObject *shapes = PyList_New(num_provided_arg_shapes);
  for (mx_uint i = 0; i < num_provided_arg_shapes; ++i) {
    mx_uint b = provided_arg_shape_idx[i], e = provided_arg_shape_idx[i + 1];
    PyObject *one = UIntList((int)(e - b), provided_arg_shape_data + b);
    PyList_SET_ITEM(shapes, i, one);
  }
  int n_buf = shared_buffer_len ? *shared_buffer_len : -1;
  if (n_buf < 0) n_buf = 0;
  PyObject *r = CallV(
      "executor_simple_bind",
      Py_BuildValue(
          "(OiiNNNNNNNNNNNNN)", (PyObject *)symbol_handle, dev_type, dev_id,
          StrList((int)num_g2c_keys, g2c_keys),
          IntList((int)num_g2c_keys, g2c_dev_types),
          IntList((int)num_g2c_keys, g2c_dev_ids),
          StrList((int)provided_grad_req_list_len, provided_grad_req_names),
          StrList((int)provided_grad_req_list_len, provided_grad_req_types),
          StrList((int)num_provided_arg_shapes, provided_arg_shape_names),
          shapes,
          StrList((int)num_provided_arg_dtypes, provided_arg_dtype_names),
          IntList((int)num_provided_arg_dtypes, provided_arg_dtypes),
          StrList((int)num_provided_arg_stypes, provided_arg_stype_names),
          IntList((int)num_provided_arg_stypes, provided_arg_stypes),
          StrList(n_buf, shared_buffer_name_list),
          HandleList(n_buf, shared_buffer_handle_list)));
  CHECK_PY(r);
  /* (ex, arg_names, in_args, arg_grads, aux_names, aux_states,
     upd_names, upd_arrays) */
  PyObject *ex = PyTuple_GetItem(r, 0);
  Py_INCREF(ex);
  *out = (ExecutorHandle)ex;
  mx_uint n = 0;
  *in_args = (NDArrayHandle *)StoreHandleList(PyTuple_GetItem(r, 2), &n);
  *num_in_args = n;
  /* arg grads share the handles vector; stash after in_args */
  static thread_local std::vector<void *> grad_handles, aux_handles,
      upd_handles;
  grad_handles.clear();
  PyObject *gl = PyTuple_GetItem(r, 3);
  for (Py_ssize_t i = 0; i < PySequence_Size(gl); ++i) {
    PyObject *it = PySequence_GetItem(gl, i);
    if (it == Py_None) { grad_handles.push_back(nullptr); Py_DECREF(it); }
    else grad_handles.push_back((void *)it);  /* keep ref */
  }
  *arg_grads = grad_handles.data();
  aux_handles.clear();
  PyObject *al = PyTuple_GetItem(r, 5);
  for (Py_ssize_t i = 0; i < PySequence_Size(al); ++i)
    aux_handles.push_back((void *)PySequence_GetItem(al, i));
  *aux_states = aux_handles.data();
  *num_aux_states = (mx_uint)aux_handles.size();
  if (updated_shared_buffer_name_list && shared_buffer_len) {
    mx_uint nu = 0;
    *updated_shared_buffer_name_list =
        StoreStrList(PyTuple_GetItem(r, 6), &nu);
    upd_handles.clear();
    PyObject *ul = PyTuple_GetItem(r, 7);
    for (Py_ssize_t i = 0; i < PySequence_Size(ul); ++i)
      upd_handles.push_back((void *)PySequence_GetItem(ul, i));
    *updated_shared_buffer_handle_list = upd_handles.data();
    *shared_buffer_len = (int)nu;
  }
  Py_DECREF(r);
  return 0;
}

int MXExecutorSetMonitorCallback(ExecutorHandle handle,
                                 ExecutorMonitorCallback callback,
                                 void *callback_handle) {
  API_BEGIN();
  PyObject *r = CallV("executor_set_monitor_callback",
                      Py_BuildValue("(OKK)", (PyObject *)handle,
                                    (unsigned long long)(uintptr_t)callback,
                                    (unsigned long long)(uintptr_t)callback_handle));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

/* -- data iter index -- */

int MXDataIterGetIndex(DataIterHandle handle, uint64_t **out_index,
                       uint64_t *out_size) {
  API_BEGIN();
  PyObject *r = CallV("data_iter_get_index",
                      Py_BuildValue("(O)", (PyObject *)handle));
  CHECK_PY(r);
  char *buf = nullptr; Py_ssize_t blen = 0;
  PyBytes_AsStringAndSize(r, &buf, &blen);
  ret.blob.assign(buf, (size_t)blen);
  *out_index = (uint64_t *)ret.blob.data();
  *out_size = (uint64_t)(blen / sizeof(uint64_t));
  Py_DECREF(r);
  return 0;
}

/* -- symbol shallow attr -- */

int MXSymbolListAttrShallow(SymbolHandle symbol, mx_uint *out_size,
                            const char ***out) {
  API_BEGIN();
  PyObject *r = CallV("symbol_list_attr_shallow",
                      Py_BuildValue("(O)", (PyObject *)symbol));
  CHECK_PY(r);
  *out = StoreStrList(r, out_size);
  *out_size /= 2;  /* pairs, not flat strings (reference ABI) */
  Py_DECREF(r);
  return 0;
}

/* -- rtc -- */

int MXRtcCreate(char *name, mx_uint num_input, mx_uint num_output,
                char **input_names, char **output_names,
                NDArrayHandle *inputs, NDArrayHandle *outputs, char *kernel,
                RtcHandle *out) {
  API_BEGIN();
  PyObject *r = CallV(
      "rtc_create",
      Py_BuildValue("(sNNNNs)", name,
                    StrList((int)num_input, (const char *const *)input_names),
                    StrList((int)num_output, (const char *const *)output_names),
                    HandleList((int)num_input, inputs),
                    HandleList((int)num_output, outputs), kernel));
  CHECK_PY(r);
  *out = (RtcHandle)r;
  return 0;
}

int MXRtcPush(RtcHandle handle, mx_uint num_input, mx_uint num_output,
              NDArrayHandle *inputs, NDArrayHandle *outputs,
              mx_uint gridDimX, mx_uint gridDimY, mx_uint gridDimZ,
              mx_uint blockDimX, mx_uint blockDimY, mx_uint blockDimZ) {
  (void)gridDimX; (void)gridDimY; (void)gridDimZ;
  (void)blockDimX; (void)blockDimY; (void)blockDimZ;
  API_BEGIN();
  PyObject *r = CallV("rtc_push",
                      Py_BuildValue("(ONN)", (PyObject *)handle,
                                    HandleList((int)num_input, inputs),
                                    HandleList((int)num_output, outputs)));
  CHECK_PY(r); Py_DECREF(r);
  return 0;
}

int MXRtcFree(RtcHandle handle) {
  if (handle) { Gil g; Py_DECREF((PyObject *)handle); }
  return 0;
}

}  /* extern "C" */
