/*
 * recordio.cc — dmlc RecordIO framed stream, byte-compatible with
 * python/mxnet/recordio.py (and mxnet_tpu/recordio.py):
 *   uint32 magic 0xced7230a, uint32 lrecord (upper 3 bits cflag, lower
 *   29 bits length), payload, zero-padded to a 4-byte boundary.
 * Reference: dmlc-core recordio consumed by src/io/iter_image_recordio*.cc;
 * this native reader is what the threaded data pipeline iterates.
 */
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <stdexcept>
#include <string>
#include <vector>

#include "mxtpu.h"

namespace mxtpu {
namespace recordio {

constexpr uint32_t kMagic = 0xced7230a;

class Writer {
 public:
  explicit Writer(const char *path) : fp_(std::fopen(path, "wb")) {
    if (!fp_) throw std::runtime_error(std::string("cannot open ") + path);
  }
  ~Writer() {
    if (fp_) std::fclose(fp_);
  }

  void Write(const char *buf, size_t len) {
    if (len >= (1u << 29))
      throw std::runtime_error("record too large (>= 2^29 bytes)");
    uint32_t head[2] = {kMagic, static_cast<uint32_t>(len) & 0x1fffffffu};
    if (std::fwrite(head, 4, 2, fp_) != 2)
      throw std::runtime_error("recordio write failed");
    if (len && std::fwrite(buf, 1, len, fp_) != len)
      throw std::runtime_error("recordio write failed");
    static const char zeros[4] = {0, 0, 0, 0};
    size_t pad = (4 - len % 4) % 4;
    if (pad && std::fwrite(zeros, 1, pad, fp_) != pad)
      throw std::runtime_error("recordio write failed");
  }

  size_t Tell() { return static_cast<size_t>(std::ftell(fp_)); }

 private:
  FILE *fp_;
};

class Reader {
 public:
  explicit Reader(const char *path) : fp_(std::fopen(path, "rb")) {
    if (!fp_) throw std::runtime_error(std::string("cannot open ") + path);
  }
  ~Reader() {
    if (fp_) std::fclose(fp_);
  }

  // returns false at clean EOF — including a truncated (<8 byte) tail
  // from a killed writer, matching the python fallback's len(head)<8
  // check; throws only on a corrupt magic in a full header
  bool Next(const char **out, size_t *len) {
    uint32_t head[2];
    size_t got = std::fread(head, 4, 2, fp_);
    if (got < 2) return false;
    if (head[0] != kMagic)
      throw std::runtime_error("invalid RecordIO magic");
    size_t n = head[1] & 0x1fffffffu;
    buf_.resize(n);
    if (n && std::fread(buf_.data(), 1, n, fp_) != n)
      throw std::runtime_error("truncated RecordIO record");
    size_t pad = (4 - n % 4) % 4;
    if (pad) std::fseek(fp_, static_cast<long>(pad), SEEK_CUR);
    *out = buf_.data();
    *len = n;
    return true;
  }

  void Seek(size_t pos) { std::fseek(fp_, static_cast<long>(pos), SEEK_SET); }
  size_t Tell() { return static_cast<size_t>(std::ftell(fp_)); }

 private:
  FILE *fp_;
  std::vector<char> buf_;
};

}  // namespace recordio
}  // namespace mxtpu

void MXTSetLastError(const char *msg);

#define API_BEGIN() try {
#define API_END()                  \
  }                                \
  catch (const std::exception &e) { \
    MXTSetLastError(e.what());     \
    return -1;                     \
  }                                \
  return 0;

using mxtpu::recordio::Reader;
using mxtpu::recordio::Writer;

extern "C" int MXTRecordIOWriterCreate(const char *path, RecordIOHandle *out) {
  API_BEGIN();
  *out = new Writer(path);
  API_END();
}

extern "C" int MXTRecordIOWriterWrite(RecordIOHandle h, const char *buf,
                                      size_t len) {
  API_BEGIN();
  static_cast<Writer *>(h)->Write(buf, len);
  API_END();
}

extern "C" int MXTRecordIOWriterTell(RecordIOHandle h, size_t *out) {
  API_BEGIN();
  *out = static_cast<Writer *>(h)->Tell();
  API_END();
}

extern "C" int MXTRecordIOWriterFree(RecordIOHandle h) {
  API_BEGIN();
  delete static_cast<Writer *>(h);
  API_END();
}

extern "C" int MXTRecordIOReaderCreate(const char *path, RecordIOHandle *out) {
  API_BEGIN();
  *out = new Reader(path);
  API_END();
}

extern "C" int MXTRecordIOReaderNext(RecordIOHandle h, const char **out,
                                     size_t *len) {
  API_BEGIN();
  if (!static_cast<Reader *>(h)->Next(out, len)) {
    *out = nullptr;
    *len = static_cast<size_t>(-1);
  }
  API_END();
}

extern "C" int MXTRecordIOReaderSeek(RecordIOHandle h, size_t pos) {
  API_BEGIN();
  static_cast<Reader *>(h)->Seek(pos);
  API_END();
}

extern "C" int MXTRecordIOReaderTell(RecordIOHandle h, size_t *out) {
  API_BEGIN();
  *out = static_cast<Reader *>(h)->Tell();
  API_END();
}

extern "C" int MXTRecordIOReaderFree(RecordIOHandle h) {
  API_BEGIN();
  delete static_cast<Reader *>(h);
  API_END();
}
