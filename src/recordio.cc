/*
 * recordio.cc — dmlc RecordIO framed stream, byte-compatible with
 * python/mxnet/recordio.py (and mxnet_tpu/recordio.py):
 *   uint32 magic 0xced7230a, uint32 lrecord (upper 3 bits cflag, lower
 *   29 bits length), payload, zero-padded to a 4-byte boundary.
 * Reference: dmlc-core recordio consumed by src/io/iter_image_recordio*.cc;
 * this native reader is what the threaded data pipeline iterates.
 */
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <stdexcept>
#include <string>
#include <vector>

#include "mxtpu.h"

namespace mxtpu {
namespace recordio {

constexpr uint32_t kMagic = 0xced7230a;

class Writer {
 public:
  explicit Writer(const char *path) : fp_(std::fopen(path, "wb")) {
    if (!fp_) throw std::runtime_error(std::string("cannot open ") + path);
  }
  ~Writer() {
    if (fp_) std::fclose(fp_);
  }

  // dmlc magic-escape framing: the payload is split at 4-aligned
  // occurrences of the magic word (dropped on write, re-inserted on
  // read) so a reader can always resync on magic. cflag in the upper
  // 3 bits of lrecord: 0=whole, 1=begin, 2=middle, 3=end.
  void Write(const char *buf, size_t len) {
    if (len >= (1u << 29))
      throw std::runtime_error("record too large (>= 2^29 bytes)");
    size_t lower = (len >> 2) << 2;
    std::vector<size_t> hits;
    for (size_t i = 0; i < lower; i += 4) {
      uint32_t w;
      std::memcpy(&w, buf + i, 4);
      if (w == kMagic) hits.push_back(i);
    }
    if (hits.empty()) {
      WriteChunk(0, buf, len);
      return;
    }
    size_t dptr = 0;
    for (size_t j = 0; j < hits.size(); ++j) {
      WriteChunk(j == 0 ? 1 : 2, buf + dptr, hits[j] - dptr);
      dptr = hits[j] + 4;
    }
    WriteChunk(3, buf + dptr, len - dptr);
  }

  void WriteChunk(uint32_t cflag, const char *buf, size_t len) {
    uint32_t head[2] = {kMagic,
                        (cflag << 29) |
                            (static_cast<uint32_t>(len) & 0x1fffffffu)};
    if (std::fwrite(head, 4, 2, fp_) != 2)
      throw std::runtime_error("recordio write failed");
    if (len && std::fwrite(buf, 1, len, fp_) != len)
      throw std::runtime_error("recordio write failed");
    static const char zeros[4] = {0, 0, 0, 0};
    size_t pad = (4 - len % 4) % 4;
    if (pad && std::fwrite(zeros, 1, pad, fp_) != pad)
      throw std::runtime_error("recordio write failed");
  }

  size_t Tell() { return static_cast<size_t>(std::ftell(fp_)); }

 private:
  FILE *fp_;
};

class Reader {
 public:
  explicit Reader(const char *path) : fp_(std::fopen(path, "rb")) {
    if (!fp_) throw std::runtime_error(std::string("cannot open ") + path);
  }
  ~Reader() {
    if (fp_) std::fclose(fp_);
  }

  // returns false at clean EOF — including a truncated (<8 byte) tail
  // from a killed writer, matching the python fallback's len(head)<8
  // check; throws only on a corrupt magic in a full header.
  // Multi-part records (cflag 1/2/3) are reassembled with the escaped
  // magic word re-inserted at each part boundary (dmlc recordio).
  bool Next(const char **out, size_t *len) {
    uint32_t cflag;
    if (!NextChunk(&buf_, &cflag)) return false;
    if (cflag == 0) {
      *out = buf_.data();
      *len = buf_.size();
      return true;
    }
    if (cflag != 1)
      throw std::runtime_error("RecordIO stream begins mid multi-part record");
    while (true) {
      std::vector<char> part;
      uint32_t cf;
      if (!NextChunk(&part, &cf))
        throw std::runtime_error("truncated multi-part RecordIO record");
      if (cf != 2 && cf != 3)
        throw std::runtime_error("bad RecordIO continuation flag");
      const char *magic = reinterpret_cast<const char *>(&kMagic);
      buf_.insert(buf_.end(), magic, magic + 4);
      buf_.insert(buf_.end(), part.begin(), part.end());
      if (cf == 3) break;
    }
    *out = buf_.data();
    *len = buf_.size();
    return true;
  }

  bool NextChunk(std::vector<char> *out, uint32_t *cflag) {
    uint32_t head[2];
    size_t got = std::fread(head, 4, 2, fp_);
    if (got < 2) return false;
    if (head[0] != kMagic)
      throw std::runtime_error("invalid RecordIO magic");
    *cflag = head[1] >> 29;
    size_t n = head[1] & 0x1fffffffu;
    out->resize(n);
    if (n && std::fread(out->data(), 1, n, fp_) != n)
      throw std::runtime_error("truncated RecordIO record");
    size_t pad = (4 - n % 4) % 4;
    if (pad) std::fseek(fp_, static_cast<long>(pad), SEEK_CUR);
    return true;
  }

  void Seek(size_t pos) { std::fseek(fp_, static_cast<long>(pos), SEEK_SET); }
  size_t Tell() { return static_cast<size_t>(std::ftell(fp_)); }

 private:
  FILE *fp_;
  std::vector<char> buf_;
};

}  // namespace recordio
}  // namespace mxtpu

void MXTSetLastError(const char *msg);

#define API_BEGIN() try {
#define API_END()                  \
  }                                \
  catch (const std::exception &e) { \
    MXTSetLastError(e.what());     \
    return -1;                     \
  }                                \
  return 0;

using mxtpu::recordio::Reader;
using mxtpu::recordio::Writer;

extern "C" int MXTRecordIOWriterCreate(const char *path, RecordIOHandle *out) {
  API_BEGIN();
  *out = new Writer(path);
  API_END();
}

extern "C" int MXTRecordIOWriterWrite(RecordIOHandle h, const char *buf,
                                      size_t len) {
  API_BEGIN();
  static_cast<Writer *>(h)->Write(buf, len);
  API_END();
}

extern "C" int MXTRecordIOWriterTell(RecordIOHandle h, size_t *out) {
  API_BEGIN();
  *out = static_cast<Writer *>(h)->Tell();
  API_END();
}

extern "C" int MXTRecordIOWriterFree(RecordIOHandle h) {
  API_BEGIN();
  delete static_cast<Writer *>(h);
  API_END();
}

extern "C" int MXTRecordIOReaderCreate(const char *path, RecordIOHandle *out) {
  API_BEGIN();
  *out = new Reader(path);
  API_END();
}

extern "C" int MXTRecordIOReaderNext(RecordIOHandle h, const char **out,
                                     size_t *len) {
  API_BEGIN();
  if (!static_cast<Reader *>(h)->Next(out, len)) {
    *out = nullptr;
    *len = static_cast<size_t>(-1);
  }
  API_END();
}

extern "C" int MXTRecordIOReaderSeek(RecordIOHandle h, size_t pos) {
  API_BEGIN();
  static_cast<Reader *>(h)->Seek(pos);
  API_END();
}

extern "C" int MXTRecordIOReaderTell(RecordIOHandle h, size_t *out) {
  API_BEGIN();
  *out = static_cast<Reader *>(h)->Tell();
  API_END();
}

extern "C" int MXTRecordIOReaderFree(RecordIOHandle h) {
  API_BEGIN();
  delete static_cast<Reader *>(h);
  API_END();
}
