/*
 * mxtpu.h — C ABI of the TPU-native runtime library.
 *
 * The TPU-native counterpart of include/mxnet/c_api.h (reference: 146
 * MXNET_DLL functions, opaque handles, int return codes, thread-local
 * MXGetLastError). Device compute goes through XLA from Python; this
 * native layer owns what the reference keeps native around its device
 * kernels: the dependency engine (include/mxnet/engine.h:93-268), the
 * pooled storage manager (include/mxnet/storage.h), the RecordIO packed
 * stream (dmlc-core recordio, python/mxnet/recordio.py framing), and the
 * chrome-trace profiler (src/engine/profiler.h).
 */
#ifndef MXTPU_H_
#define MXTPU_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *EngineHandle;
typedef void *VarHandle;
typedef void *CompletionHandle;
typedef void *RecordIOHandle;

/* Every call returns 0 on success, -1 on failure (message via
 * MXTGetLastError — reference c_api_error.cc). */
const char *MXTGetLastError();

/* ---- Engine: async read/write-set dependency scheduler (ref N1) ---- */
/* fn runs on a worker thread. Sync ops complete on return; async ops
 * receive a completion handle and must call MXTEngineOprComplete. */
typedef void (*MXTSyncFn)(void *param);
typedef void (*MXTAsyncFn)(void *param, CompletionHandle on_complete);

int MXTEngineCreate(int num_workers, EngineHandle *out);
int MXTEngineFree(EngineHandle h);
int MXTEngineNewVar(EngineHandle h, VarHandle *out);
/* Delete is itself scheduled as a write op (reference engine.h
 * DeleteVariable: "delete after all pending ops complete"). */
int MXTEngineDeleteVar(EngineHandle h, VarHandle var);
int MXTEnginePushSync(EngineHandle h, MXTSyncFn fn, void *param,
                      VarHandle *const_vars, int num_const,
                      VarHandle *mutable_vars, int num_mutable,
                      int priority, const char *opr_name);
int MXTEnginePushAsync(EngineHandle h, MXTAsyncFn fn, void *param,
                       VarHandle *const_vars, int num_const,
                       VarHandle *mutable_vars, int num_mutable,
                       int priority, const char *opr_name);
int MXTEngineOprComplete(CompletionHandle token);
int MXTEngineWaitForVar(EngineHandle h, VarHandle var);
int MXTEngineWaitForAll(EngineHandle h);
/* pending op count (for tests / shutdown diagnostics) */
int MXTEnginePendingOps(EngineHandle h, int64_t *out);

/* ---- Storage: pooled, aligned host allocator (ref N2) ---- */
int MXTStorageAlloc(size_t nbytes, void **out);
int MXTStorageFree(void *ptr);           /* returns block to the pool */
int MXTStorageDirectFree(void *ptr);     /* bypasses the pool */
int MXTStorageReleaseAll();              /* drop all pooled blocks */
/* stats: [0] bytes live, [1] bytes pooled, [2] alloc calls,
 * [3] pool hits */
int MXTStorageStats(int64_t stats[4]);

/* ---- RecordIO: dmlc framed record stream (ref N12) ---- */
int MXTRecordIOWriterCreate(const char *path, RecordIOHandle *out);
int MXTRecordIOWriterWrite(RecordIOHandle h, const char *buf, size_t len);
int MXTRecordIOWriterTell(RecordIOHandle h, size_t *out);
int MXTRecordIOWriterFree(RecordIOHandle h);
int MXTRecordIOReaderCreate(const char *path, RecordIOHandle *out);
/* *out points into an internal buffer valid until the next call. Sets
 * *len = SIZE_MAX (i.e. (size_t)-1) at end of stream. */
int MXTRecordIOReaderNext(RecordIOHandle h, const char **out, size_t *len);
int MXTRecordIOReaderSeek(RecordIOHandle h, size_t pos);
int MXTRecordIOReaderTell(RecordIOHandle h, size_t *out);
int MXTRecordIOReaderFree(RecordIOHandle h);

/* ---- Profiler: chrome trace-event JSON (ref N16) ---- */
int MXTProfilerSetState(int running);
/* records engine op execution spans when running; explicit events may
 * be added from any thread */
int MXTProfilerAddEvent(const char *name, const char *category,
                        int64_t start_us, int64_t end_us);
int MXTProfilerDump(const char *path);
int64_t MXTNowUS();

#ifdef __cplusplus
}
#endif

#endif /* MXTPU_H_ */
