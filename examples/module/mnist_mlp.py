"""Module API tour — reference example/module/mnist_mlp.py: the
low-level Module workflow (bind / init / forward / backward / update
loop), then fit() with checkpointing and resume from a saved epoch.
Hermetic blobs stand in for MNIST.

    python mnist_mlp.py --epochs 6
"""
import argparse
import logging
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx

NCLASS = 10
DIM = 64


def net_symbol():
    data = mx.sym.Variable('data')
    net = mx.sym.FullyConnected(data, name='fc1', num_hidden=64)
    net = mx.sym.Activation(net, name='relu1', act_type='relu')
    net = mx.sym.FullyConnected(net, name='fc2', num_hidden=NCLASS)
    return mx.sym.SoftmaxOutput(net, name='softmax')


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=6)
    ap.add_argument('--batch-size', type=int, default=64)
    ap.add_argument('--lr', type=float, default=0.1)
    ap.add_argument('--min-acc', type=float, default=0.95)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(1)

    rng = np.random.RandomState(7)
    centers = rng.randn(NCLASS, DIM).astype(np.float32) * 2.0
    lab = rng.randint(0, NCLASS, 640)
    x = (centers[lab] + 0.4 * rng.randn(640, DIM)).astype(np.float32)
    train = mx.io.NDArrayIter(x, lab.astype(np.float32), args.batch_size,
                              shuffle=True, label_name='softmax_label')

    # --- 1. raw intermediate-level loop (reference mnist_mlp.py style)
    mod = mx.mod.Module(net_symbol(), label_names=('softmax_label',))
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer='sgd',
                       optimizer_params={'learning_rate': args.lr,
                                         'momentum': 0.9})
    metric = mx.metric.Accuracy()
    for epoch in range(args.epochs):
        train.reset()
        metric.reset()
        for batch in train:
            mod.forward(batch, is_train=True)
            mod.update_metric(metric, batch.label)
            mod.backward()
            mod.update()
        logging.info('raw-loop epoch %d %s', epoch, metric.get())
    acc_raw = metric.get()[1]

    # --- 2. fit() with per-epoch checkpointing, then resume
    train.reset()          # fit() expects a fresh iterator (ref contract)
    prefix = os.path.join(tempfile.mkdtemp(), 'mlp')
    mod2 = mx.mod.Module(net_symbol(), label_names=('softmax_label',))
    half = max(1, args.epochs // 2)
    mod2.fit(train, num_epoch=half, optimizer='sgd',
             optimizer_params={'learning_rate': args.lr, 'momentum': 0.9},
             initializer=mx.init.Xavier(),
             epoch_end_callback=mx.callback.do_checkpoint(prefix))
    sym, arg_params, aux_params = mx.model.load_checkpoint(prefix, half)
    mod3 = mx.mod.Module(sym, label_names=('softmax_label',))
    mod3.fit(train, num_epoch=args.epochs, arg_params=arg_params,
             aux_params=aux_params, begin_epoch=half, optimizer='sgd',
             optimizer_params={'learning_rate': args.lr, 'momentum': 0.9})
    acc_resumed = dict(mod3.score(train, ['acc']))['accuracy']

    logging.info('raw-loop acc %.3f, checkpoint-resumed acc %.3f',
                 acc_raw, acc_resumed)
    assert acc_raw >= args.min_acc, acc_raw
    assert acc_resumed >= args.min_acc, acc_resumed
    print('module_mnist_mlp: raw=%.3f resumed=%.3f' % (acc_raw, acc_resumed))


if __name__ == '__main__':
    main()
