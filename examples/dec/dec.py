"""Deep Embedded Clustering — reference example/dec/dec.py (Xie et al.
2016): pretrain an autoencoder, initialize cluster centroids with
k-means in code space, then refine encoder + centroids against the
sharpened auxiliary target distribution (KL self-training). Hermetic:
Gaussian clusters embedded through a fixed nonlinear map, so the true
partition is recoverable.

    python dec.py --pretrain-epochs 10 --dec-iters 60
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

DIM = 48
NCLUST = 4
NZ = 6


def cluster_acc(pred, truth):
    """Best-matching assignment accuracy (Hungarian-lite: greedy works
    for well-separated synthetic clusters)."""
    remaining = set(range(NCLUST))
    total = 0
    for c in range(NCLUST):
        best, best_n = None, -1
        for t in remaining:
            n = int(((pred == c) & (truth == t)).sum())
            if n > best_n:
                best, best_n = t, n
        remaining.discard(best)
        total += best_n
    return total / len(pred)


def make_data(rng, n):
    centers = rng.randn(NCLUST, NZ).astype(np.float32) * 3.0
    lab = rng.randint(0, NCLUST, n)
    z = centers[lab] + 0.4 * rng.randn(n, NZ).astype(np.float32)
    mix = rng.randn(NZ, DIM).astype(np.float32)
    x = np.tanh(z @ mix) + 0.05 * rng.randn(n, DIM).astype(np.float32)
    return x.astype(np.float32), lab


def kmeans(z, k, rng, iters=20):
    cent = z[rng.choice(len(z), k, replace=False)].copy()
    for _ in range(iters):
        d = ((z[:, None] - cent[None]) ** 2).sum(-1)
        a = d.argmin(1)
        for c in range(k):
            if (a == c).any():
                cent[c] = z[a == c].mean(0)
    return cent


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--pretrain-epochs', type=int, default=10)
    ap.add_argument('--dec-iters', type=int, default=60)
    ap.add_argument('--samples', type=int, default=768)
    ap.add_argument('--lr', type=float, default=2e-3)
    ap.add_argument('--min-acc', type=float, default=0.9)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(6)

    rng = np.random.RandomState(17)
    x, truth = make_data(rng, args.samples)

    enc = nn.Sequential()
    dec_net = nn.Sequential()
    with enc.name_scope():
        enc.add(nn.Dense(32, activation='tanh'), nn.Dense(NZ))
    with dec_net.name_scope():
        dec_net.add(nn.Dense(32, activation='tanh'), nn.Dense(DIM))
    enc.initialize(mx.init.Xavier())
    dec_net.initialize(mx.init.Xavier())

    # --- autoencoder pretraining
    params = list(enc.collect_params().values()) + \
        list(dec_net.collect_params().values())
    trainer = gluon.Trainer(enc.collect_params(), 'adam',
                            {'learning_rate': args.lr})
    trainer2 = gluon.Trainer(dec_net.collect_params(), 'adam',
                             {'learning_rate': args.lr})
    l2 = gluon.loss.L2Loss()
    for epoch in range(args.pretrain_epochs):
        perm = rng.permutation(len(x))
        tot = 0.0
        for i in range(0, len(x), 64):
            data = mx.nd.array(x[perm[i:i + 64]])
            with autograd.record():
                loss = l2(dec_net(enc(data)), data)
            loss.backward()
            trainer.step(data.shape[0])
            trainer2.step(data.shape[0])
            tot += float(loss.mean().asscalar()) * data.shape[0]
        logging.info('pretrain epoch %d mse %.5f', epoch, tot / len(x))

    # --- centroid init by k-means in code space
    z = enc(mx.nd.array(x)).asnumpy()
    centroids = mx.nd.array(kmeans(z, NCLUST, rng))
    centroids.attach_grad()

    def soft_assign(z_nd):
        """Student-t similarity (DEC eq. 1)."""
        d2 = ((z_nd.expand_dims(1) - centroids.expand_dims(0)) ** 2).sum(-1)
        q = 1.0 / (1.0 + d2)
        return q / q.sum(axis=1, keepdims=True)

    # --- DEC refinement: KL(p || q) with sharpened targets
    for it in range(args.dec_iters):
        data = mx.nd.array(x)
        qn = soft_assign(enc(data))
        p = (qn ** 2 / qn.sum(axis=0, keepdims=True)).asnumpy()
        p = mx.nd.array(p / p.sum(axis=1, keepdims=True))
        with autograd.record():
            q = soft_assign(enc(data))
            kl = (p * ((p + 1e-10).log() - (q + 1e-10).log())).sum(axis=1)
            loss = kl.mean()
        loss.backward()
        trainer.step(len(x))
        centroids -= args.lr * 10 * centroids.grad
        if it % 15 == 0:
            pred = q.asnumpy().argmax(1)
            logging.info('dec iter %d kl %.5f acc %.3f', it,
                         float(loss.asscalar()), cluster_acc(pred, truth))

    pred = soft_assign(enc(mx.nd.array(x))).asnumpy().argmax(1)
    acc = cluster_acc(pred, truth)
    logging.info('final cluster accuracy %.3f', acc)
    assert acc >= args.min_acc, 'DEC failed: %.3f' % acc
    print('dec: cluster_acc=%.3f' % acc)


if __name__ == '__main__':
    main()
