"""Matrix-factorization recommender — reference example/recommenders
(demo1-MF): user/item Embedding factors trained on ratings with L2
loss, the classic collaborative-filtering baseline.

Hermetic: ratings come from a planted low-rank model plus noise, so the
learned factors must recover it — test RMSE is asserted against the
noise floor.

    python matrix_fact.py --epochs 8
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.gluon import nn


class MFBlock(gluon.Block):
    def __init__(self, n_users, n_items, k, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.user = nn.Embedding(n_users, k)
            self.item = nn.Embedding(n_items, k)
            self.user_b = nn.Embedding(n_users, 1)
            self.item_b = nn.Embedding(n_items, 1)

    def forward(self, users, items):
        p = self.user(users)
        q = self.item(items)
        return ((p * q).sum(axis=1) + self.user_b(users).reshape((-1,)) +
                self.item_b(items).reshape((-1,)))


def planted_ratings(rng, n_users, n_items, k, n_obs, noise=0.1):
    U = rng.randn(n_users, k) / np.sqrt(k)
    V = rng.randn(n_items, k) / np.sqrt(k)
    bu = rng.randn(n_users) * 0.3
    bi = rng.randn(n_items) * 0.3
    u = rng.randint(0, n_users, n_obs)
    i = rng.randint(0, n_items, n_obs)
    r = (U[u] * V[i]).sum(1) + bu[u] + bi[i] + noise * rng.randn(n_obs)
    return (u.astype(np.float32), i.astype(np.float32),
            r.astype(np.float32))


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=8)
    p.add_argument('--batch-size', type=int, default=512)
    p.add_argument('--users', type=int, default=200)
    p.add_argument('--items', type=int, default=150)
    p.add_argument('--rank', type=int, default=8)
    p.add_argument('--obs', type=int, default=8000)
    p.add_argument('--lr', type=float, default=0.05)
    p.add_argument('--noise', type=float, default=0.1)
    p.add_argument('--seed', type=int, default=0)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    u, i, r = planted_ratings(rng, args.users, args.items, args.rank,
                              args.obs, args.noise)
    n_train = int(0.9 * args.obs)
    net = MFBlock(args.users, args.items, args.rank)
    net.initialize(mx.init.Normal(0.05))
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})
    l2 = gluon.loss.L2Loss()

    for epoch in range(args.epochs):
        perm = rng.permutation(n_train)
        tot = cnt = 0
        for s in range(0, n_train, args.batch_size):
            idx = perm[s:s + args.batch_size]
            bu = mx.nd.array(u[idx])
            bi = mx.nd.array(i[idx])
            br = mx.nd.array(r[idx])
            with autograd.record():
                loss = l2(net(bu, bi), br).mean()
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy()) * len(idx)
            cnt += len(idx)
        pred = net(mx.nd.array(u[n_train:]),
                   mx.nd.array(i[n_train:])).asnumpy()
        rmse = float(np.sqrt(np.mean((pred - r[n_train:]) ** 2)))
        logging.info('epoch %d train-loss %.4f test RMSE %.3f', epoch,
                     tot / cnt, rmse)
    # the planted noise floor is `noise`; require getting close to it
    assert rmse < 3.0 * args.noise, 'RMSE too high: %.3f' % rmse
    print('matrix factorization ok: test RMSE %.3f (noise %.2f)'
          % (rmse, args.noise))


if __name__ == '__main__':
    main()
