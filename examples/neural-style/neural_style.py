"""Neural style transfer — reference example/neural-style/nstyle.py
(Gatys et al.): optimize the pixels of an image so a conv net's deep
features match a content image while the Gram matrices of shallower
features match a style image. Hermetic: the feature extractor is a
fixed random conv stack (style transfer needs fixed features, not
trained ones) and content/style images are synthetic textures.

    python neural_style.py --steps 150
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

HW = 32


class Features(gluon.Block):
    """Fixed random conv stack; returns (style_feats, content_feat)."""

    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.c1 = nn.Conv2D(8, 3, padding=1, activation='relu')
            self.c2 = nn.Conv2D(16, 3, strides=2, padding=1,
                                activation='relu')
            self.c3 = nn.Conv2D(32, 3, strides=2, padding=1,
                                activation='relu')

    def forward(self, x):
        f1 = self.c1(x)
        f2 = self.c2(f1)
        f3 = self.c3(f2)
        return [f1, f2], f3


def gram(f):
    """Channel co-occurrence matrix (style representation)."""
    b, c, h, w = f.shape
    m = f.reshape((c, h * w))
    return mx.nd.dot(m, m.T) / (c * h * w)


def texture(rng, freq):
    yy, xx = np.meshgrid(np.linspace(0, 1, HW), np.linspace(0, 1, HW),
                         indexing='ij')
    img = np.zeros((HW, HW), np.float32)
    for _ in range(4):
        fy, fx = rng.rand(2) * freq
        img += np.sin(2 * np.pi * (fy * yy + fx * xx) + rng.rand() * 6.28)
    return img[None, None].astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--steps', type=int, default=150)
    ap.add_argument('--lr', type=float, default=0.05)
    ap.add_argument('--style-weight', type=float, default=50.0)
    ap.add_argument('--min-drop', type=float, default=0.8,
                    help='required relative total-loss drop')
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(11)

    rng = np.random.RandomState(21)
    content_img = mx.nd.array(texture(rng, 2.0))   # low-freq "photo"
    style_img = mx.nd.array(texture(rng, 8.0))     # high-freq "painting"

    net = Features()
    net.initialize(mx.init.Xavier())               # fixed random weights

    style_feats, _ = net(style_img)
    style_grams = [gram(f) for f in style_feats]
    _, content_feat = net(content_img)

    img = content_img.copy() + 0.1 * mx.nd.random.normal(
        shape=content_img.shape)
    img.attach_grad()
    trainer_like_lr = args.lr

    first = last = None
    for step in range(args.steps):
        with autograd.record():
            feats, cfeat = net(img)
            content_loss = ((cfeat - content_feat) ** 2).mean()
            style_loss = sum(((gram(f) - g) ** 2).sum()
                             for f, g in zip(feats, style_grams))
            loss = content_loss + args.style_weight * style_loss
        loss.backward()
        img -= trainer_like_lr * img.grad / \
            (mx.nd.abs(img.grad).mean() + 1e-8)    # normalized GD (ref trick)
        v = float(loss.asscalar())
        if first is None:
            first = v
        last = v
        if step % 25 == 0:
            logging.info('step %d loss %.5f (content %.5f style %.5f)',
                         step, v, float(content_loss.asscalar()),
                         float(style_loss.asscalar()))

    drop = 1.0 - last / first
    logging.info('loss %.5f -> %.5f (drop %.1f%%)', first, last, 100 * drop)
    assert drop >= args.min_drop, 'style optimization stalled: %.3f' % drop
    print('neural_style: loss_drop=%.3f' % drop)


if __name__ == '__main__':
    main()
