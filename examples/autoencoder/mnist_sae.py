"""Stacked (denoising) autoencoder — reference example/autoencoder/
mnist_sae.py + autoencoder.py/model.py: greedy layer-wise pretraining of
each encoder/decoder pair, then end-to-end fine-tuning, scored by
reconstruction MSE. Hermetic: band-limited synthetic images stand in
for MNIST so the low-dimensional code is exactly learnable.

    python mnist_sae.py --pretrain-epochs 6 --finetune-epochs 8
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

DIM = 64 * 4  # 16x16 images, flattened


def images(rng, n):
    """Low-rank images: random mixtures of 8 fixed smooth basis images."""
    yy, xx = np.meshgrid(np.linspace(0, 1, 16), np.linspace(0, 1, 16),
                         indexing='ij')
    basis = [np.sin(2 * np.pi * (fx * xx + fy * yy))
             for fx, fy in [(1, 0), (0, 1), (1, 1), (2, 0),
                            (0, 2), (2, 1), (1, 2), (2, 2)]]
    basis = np.stack([b.ravel() for b in basis])          # (8, 256)
    codes = rng.randn(n, 8).astype(np.float32)
    x = codes @ basis.astype(np.float32)
    return (x / np.abs(x).max()).astype(np.float32)


class AELayer(gluon.Block):
    def __init__(self, n_in, n_hidden, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.enc = nn.Dense(n_hidden, activation='tanh', in_units=n_in)
            self.dec = nn.Dense(n_in, in_units=n_hidden)

    def forward(self, x):
        return self.dec(self.enc(x))


def train(block, forward, x, epochs, lr, rng, noise=0.0, tag=''):
    trainer = gluon.Trainer(block.collect_params(), 'adam',
                            {'learning_rate': lr})
    loss_fn = gluon.loss.L2Loss()
    n = len(x)
    for epoch in range(epochs):
        perm = rng.permutation(n)
        tot = 0.0
        for i in range(0, n, 64):
            idx = perm[i:i + 64]
            clean = mx.nd.array(x[idx])
            noisy = clean
            if noise:
                noisy = clean + noise * mx.nd.array(
                    rng.randn(*clean.shape).astype(np.float32))
            with autograd.record():
                loss = loss_fn(forward(noisy), clean)
            loss.backward()
            trainer.step(len(idx))
            tot += float(loss.mean().asscalar()) * len(idx)
        logging.info('%s epoch %d loss %.5f', tag, epoch, tot / n)
    return tot / n


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--pretrain-epochs', type=int, default=6)
    ap.add_argument('--finetune-epochs', type=int, default=8)
    ap.add_argument('--samples', type=int, default=768)
    ap.add_argument('--lr', type=float, default=2e-3)
    ap.add_argument('--max-mse', type=float, default=0.01,
                    help='required final reconstruction L2Loss')
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(5)
    x = images(rng, args.samples)

    l1 = AELayer(DIM, 64)
    l2 = AELayer(64, 16)
    for layer in (l1, l2):
        layer.initialize(mx.init.Xavier())

    # greedy layer-wise pretraining (reference model.py layerwise loop)
    train(l1, lambda v: l1(v), x, args.pretrain_epochs, args.lr, rng,
          noise=0.1, tag='pretrain-l1')
    h = l1.enc(mx.nd.array(x)).asnumpy()
    train(l2, lambda v: l2(v), h, args.pretrain_epochs, args.lr, rng,
          noise=0.1, tag='pretrain-l2')

    # end-to-end fine-tune of the unrolled stack
    class Stack(gluon.Block):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.l1, self.l2 = l1, l2

        def forward(self, v):
            return self.l1.dec(self.l2(self.l1.enc(v)))

    stack = Stack()
    final = train(stack, stack, x, args.finetune_epochs, args.lr, rng,
                  tag='finetune')
    assert final < args.max_mse, 'reconstruction too lossy: %.5f' % final
    print('mnist_sae: final_mse=%.5f' % final)


if __name__ == '__main__':
    main()
