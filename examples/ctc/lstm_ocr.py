"""LSTM + CTC sequence recognition — reference example/ctc/lstm_ocr.py
(warp-ctc captcha OCR): an LSTM reads image columns and CTC aligns the
per-column predictions to an unsegmented digit-sequence label.
Hermetic: each digit is a fixed random glyph of 3 columns, sequences
vary in length 3-5, rendered with jitter; greedy CTC decode is scored
by full-sequence match.

    python lstm_ocr.py --epochs 25
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn, rnn

NDIGIT = 10          # alphabet 1..10, blank 0
GLYPH_W = 3          # columns per glyph
H = 12               # rows per column
MAXLEN = 5
T = MAXLEN * GLYPH_W + 2


class OCRNet(gluon.Block):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.lstm = rnn.LSTM(48, num_layers=1, bidirectional=True)
            self.fc = nn.Dense(NDIGIT + 1, flatten=False)

    def forward(self, x):          # x: (T, N, H)
        return self.fc(self.lstm(x))   # (T, N, NDIGIT+1)


def make_data(rng, n, glyphs):
    xs = np.zeros((n, T, H), np.float32)
    labels = np.full((n, MAXLEN), -1, np.float32)
    for i in range(n):
        k = rng.randint(3, MAXLEN + 1)
        digits = rng.randint(0, NDIGIT, k)
        col = 1
        for j, d in enumerate(digits):
            xs[i, col:col + GLYPH_W] = glyphs[d]
            col += GLYPH_W
            labels[i, j] = d + 1          # 0 is the CTC blank
        xs[i] += 0.1 * rng.randn(T, H)
    return xs, labels


def greedy_decode(logits):
    """Collapse repeats then drop blanks (standard CTC greedy path)."""
    best = logits.argmax(axis=-1)         # (T, N)
    out = []
    for n in range(best.shape[1]):
        seq, prev = [], 0
        for t in range(best.shape[0]):
            c = int(best[t, n])
            if c != 0 and c != prev:
                seq.append(c)
            prev = c
        out.append(seq)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=25)
    ap.add_argument('--samples', type=int, default=384)
    ap.add_argument('--batch-size', type=int, default=64)
    ap.add_argument('--lr', type=float, default=1e-2)
    ap.add_argument('--min-seq-acc', type=float, default=0.85)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(1)

    rng = np.random.RandomState(2)
    glyphs = rng.randn(NDIGIT, GLYPH_W, H).astype(np.float32)
    xs, labels = make_data(rng, args.samples, glyphs)
    xte, lte = make_data(rng, args.samples // 4, glyphs)

    net = OCRNet()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})
    # TNC layout straight out of the LSTM; padding_mask -1
    ctc = gluon.loss.CTCLoss(layout='TNC', label_layout='NT')

    for epoch in range(args.epochs):
        perm = rng.permutation(len(xs))
        tot = 0.0
        for i in range(0, len(xs), args.batch_size):
            idx = perm[i:i + args.batch_size]
            data = mx.nd.array(xs[idx].transpose(1, 0, 2))   # (T,N,H)
            lab = mx.nd.array(labels[idx])
            with autograd.record():
                loss = ctc(net(data), lab).mean()
            loss.backward()
            trainer.step(len(idx))
            tot += float(loss.asscalar()) * len(idx)
        logging.info('epoch %d ctc loss %.4f', epoch, tot / len(xs))

    logits = net(mx.nd.array(xte.transpose(1, 0, 2))).asnumpy()
    decoded = greedy_decode(logits)
    truth = [[int(v) for v in row if v > 0] for row in lte]
    acc = float(np.mean([d == t for d, t in zip(decoded, truth)]))
    logging.info('sequence accuracy %.3f', acc)
    assert acc >= args.min_seq_acc, 'CTC OCR failed: seq acc %.3f' % acc
    print('lstm_ocr: seq_acc=%.3f' % acc)


if __name__ == '__main__':
    main()
