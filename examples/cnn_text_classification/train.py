"""CNN text classification (Kim 2014) — reference
example/cnn_text_classification/: parallel 1D convolutions of several
filter widths over word embeddings, max-over-time pooling, dropout, FC.

Hermetic synthetic task: sequences over a vocabulary where the class is
determined by which "pattern" bigrams appear — exactly the structure
width-2+ text filters exist to detect.

    python train.py --epochs 3
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx


def cnn_text_symbol(vocab, embed, seq_len, filters=(2, 3, 4),
                    num_filter=16, num_classes=2, dropout=0.3):
    data = mx.sym.Variable('data')                       # (B, seq)
    emb = mx.sym.Embedding(data, input_dim=vocab, output_dim=embed,
                           name='embed')                 # (B, seq, E)
    x = mx.sym.Reshape(emb, shape=(0, 1, seq_len, embed))
    pooled = []
    for fw in filters:
        c = mx.sym.Convolution(x, kernel=(fw, embed), num_filter=num_filter,
                               name='conv%d' % fw)       # (B, F, seq-fw+1, 1)
        a = mx.sym.Activation(c, act_type='relu')
        p = mx.sym.Pooling(a, kernel=(seq_len - fw + 1, 1), pool_type='max')
        pooled.append(p)                                 # (B, F, 1, 1)
    h = mx.sym.Concat(*pooled, dim=1)
    h = mx.sym.Flatten(h)
    h = mx.sym.Dropout(h, p=dropout)
    fc = mx.sym.FullyConnected(h, num_hidden=num_classes, name='fc')
    return mx.sym.SoftmaxOutput(fc, name='softmax')


def synthetic_text(n, vocab, seq_len, seed=0):
    """Class 1 iff one of two signal bigrams occurs."""
    rng = np.random.RandomState(seed)
    bigrams = [(7, 3), (11, 5)]
    X = rng.randint(12, vocab, size=(n, seq_len))
    y = rng.randint(0, 2, size=n)
    for i in range(n):
        if y[i]:
            pos = rng.randint(0, seq_len - 1)
            X[i, pos:pos + 2] = bigrams[rng.randint(2)]
    return X.astype(np.float32), y.astype(np.float32)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--epochs', type=int, default=3)
    parser.add_argument('--batch-size', type=int, default=32)
    parser.add_argument('--samples', type=int, default=512)
    parser.add_argument('--vocab', type=int, default=64)
    parser.add_argument('--embed', type=int, default=16)
    parser.add_argument('--seq-len', type=int, default=24)
    parser.add_argument('--lr', type=float, default=0.05)
    parser.add_argument('--seed', type=int, default=0)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)

    X, y = synthetic_text(args.samples, args.vocab, args.seq_len,
                          seed=args.seed)
    Xv, yv = synthetic_text(128, args.vocab, args.seq_len,
                            seed=args.seed + 1)
    train = mx.io.NDArrayIter(X, y, batch_size=args.batch_size,
                              shuffle=True, label_name='softmax_label')
    val = mx.io.NDArrayIter(Xv, yv, batch_size=args.batch_size,
                            label_name='softmax_label')

    net = cnn_text_symbol(args.vocab, args.embed, args.seq_len)
    mod = mx.mod.Module(net, label_names=['softmax_label'])
    mod.fit(train, eval_data=val, num_epoch=args.epochs, optimizer='adam',
            optimizer_params={'learning_rate': args.lr},
            initializer=mx.init.Xavier(),
            eval_metric='acc')
    score = dict(mod.score(val, 'acc'))
    logging.info('val accuracy %.3f', score['accuracy'])
    assert score['accuracy'] > 0.85, score
    print('cnn text classification ok: %.3f' % score['accuracy'])


if __name__ == '__main__':
    main()
