"""Multi-task training — reference example/multi-task/example_multi_task.py:
one shared trunk with two softmax heads (digit class + a derived binary
task), trained jointly through a Group symbol with a per-head accuracy
metric. Hermetic blobs stand in for MNIST; task 2 is parity of the
class index.

    python example_multi_task.py --epochs 10
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx

NCLASS = 10
DIM = 32


def build_network():
    data = mx.sym.Variable('data')
    fc1 = mx.sym.FullyConnected(data=data, num_hidden=64, name='fc1')
    act1 = mx.sym.Activation(data=fc1, act_type='relu', name='relu1')
    fc2 = mx.sym.FullyConnected(data=act1, num_hidden=NCLASS, name='fc2')
    sm1 = mx.sym.SoftmaxOutput(data=fc2, name='softmax1')
    fc3 = mx.sym.FullyConnected(data=act1, num_hidden=2, name='fc3')
    sm2 = mx.sym.SoftmaxOutput(data=fc3, name='softmax2')
    return mx.sym.Group([sm1, sm2])


class MultiAccuracy(mx.metric.EvalMetric):
    """Reference example_multi_task.py Multi_Accuracy: one accuracy
    per output head."""

    def __init__(self, num=2):
        self.num = num
        super().__init__('multi-accuracy')

    def reset(self):
        self.num_inst = [0] * self.num
        self.sum_metric = [0.0] * self.num

    def update(self, labels, preds):
        for i in range(self.num):
            pred = preds[i].asnumpy().argmax(axis=1)
            lab = labels[i].asnumpy().astype(np.int64).ravel()
            self.sum_metric[i] += (pred == lab).sum()
            self.num_inst[i] += len(lab)

    def get(self):
        accs = [s / max(n, 1)
                for s, n in zip(self.sum_metric, self.num_inst)]
        return (['task%d-acc' % i for i in range(self.num)], accs)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=10)
    ap.add_argument('--samples', type=int, default=640)
    ap.add_argument('--batch-size', type=int, default=64)
    ap.add_argument('--lr', type=float, default=0.1)
    ap.add_argument('--min-acc', type=float, default=0.9)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(4)
    centers = rng.randn(NCLASS, DIM).astype(np.float32) * 2.0
    lab = rng.randint(0, NCLASS, args.samples)
    x = (centers[lab] + 0.4 * rng.randn(args.samples, DIM)).astype(np.float32)
    y1 = lab.astype(np.float32)
    y2 = (lab % 2).astype(np.float32)

    train = mx.io.NDArrayIter(x, {'softmax1_label': y1,
                                  'softmax2_label': y2},
                              args.batch_size, shuffle=True)

    mod = mx.mod.Module(build_network(),
                        label_names=('softmax1_label', 'softmax2_label'))
    metric = MultiAccuracy()
    mod.fit(train, eval_metric=metric, optimizer='sgd',
            optimizer_params={'learning_rate': args.lr, 'momentum': 0.9},
            num_epoch=args.epochs)

    metric.reset()
    train.reset()
    for batch in train:
        mod.forward(batch, is_train=False)
        metric.update(batch.label, mod.get_outputs())
    names, accs = metric.get()
    logging.info('final %s', dict(zip(names, accs)))
    assert all(a >= args.min_acc for a in accs), dict(zip(names, accs))
    print('multi_task: ' +
          ' '.join('%s=%.3f' % (n, a) for n, a in zip(names, accs)))


if __name__ == '__main__':
    main()
