"""5-axis parallel transformer LM training — the TPU-native successor to
example/model-parallel-lstm in the reference.

The reference's model parallelism is manual layer placement over GPUs
(lstm.py group2ctx); here ONE compiled program shards over a named mesh:
data (dp), tensor (tp), pipeline (pp), sequence (sp, ring attention) and
expert (ep, MoE) — see mxnet_tpu/parallel/five_d.py.

Runs on any device count (axes of size 1 degrade gracefully). On a CPU
host, set XLA_FLAGS=--xla_force_host_platform_device_count=8 to simulate
8 devices.

    python train_5d_transformer.py --pp 2 --dp 2 --tp 2 --steps 20
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--dp', type=int, default=1)
    parser.add_argument('--tp', type=int, default=1)
    parser.add_argument('--pp', type=int, default=1)
    parser.add_argument('--sp', type=int, default=1)
    parser.add_argument('--ep', type=int, default=1)
    parser.add_argument('--steps', type=int, default=20)
    parser.add_argument('--d-model', type=int, default=64)
    parser.add_argument('--vocab', type=int, default=128)
    parser.add_argument('--seq', type=int, default=32)
    parser.add_argument('--batch', type=int, default=8)
    parser.add_argument('--lr', type=float, default=0.3)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    import jax.numpy as jnp
    from mxnet_tpu.parallel.five_d import (TransformerConfig, full_mesh,
                                           make_5d_train_step)

    mesh = full_mesh({'dp': args.dp, 'tp': args.tp, 'pp': args.pp,
                      'sp': args.sp, 'ep': args.ep})
    logging.info('mesh: %s', mesh)
    cfg = TransformerConfig(vocab=args.vocab, d_model=args.d_model,
                            n_heads=max(4, args.tp), ffn=2 * args.d_model,
                            experts=max(2, args.ep),
                            n_layers=2 * args.pp)
    init_state, step = make_5d_train_step(cfg, mesh, lr=args.lr)
    state = init_state(seed=0)

    rng = np.random.RandomState(0)
    n_micro = args.pp + 1
    toks = jnp.asarray(rng.randint(0, cfg.vocab,
                                   (n_micro, args.batch, args.seq)), jnp.int32)
    # next-token prediction targets (shifted input)
    tgts = jnp.concatenate([toks[:, :, 1:], toks[:, :, :1]], axis=-1)

    for i in range(args.steps):
        state, loss = step(state, toks, tgts)
        if i % 5 == 0 or i == args.steps - 1:
            logging.info('step %d loss %.4f', i, float(loss))
    return float(loss)


if __name__ == '__main__':
    main()
