"""Long-context training via sequence parallelism (ring attention).

The long-context story end to end: a causal transformer LM whose
sequence dimension is SHARDED over the mesh's `sp` axis — activations
for a seq-L batch never exist whole on one device; attention runs as
ring attention (K/V blocks rotate around the ring via ppermute,
arXiv:2310.01889) inside the same jitted SPMD train step as dp-sharded
data parallelism.

Trains on a synthetic needle-detection task that REQUIRES long-range
attention: the prediction at the FINAL position is whether a needle
token appeared in the first eighth of the sequence — on the sp mesh
that information lives on a different device, so the gradient path runs
through the rotating K/V ring. Loss at the answer position must beat
the 2-way uniform baseline.

    python train_long_context.py --sp 4 --dp 2 --seq 256 --steps 200
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from mxnet_tpu.parallel import shard_map  # version-stable kwarg spelling

from mxnet_tpu import parallel as par
from mxnet_tpu.parallel.ring_attention import (ring_attention,
                                                striped_attention,
                                                ulysses_attention)


def make_model_fns(vocab, d_model, n_heads, attn='ring'):
    head_dim = d_model // n_heads

    def init(key):
        ks = jax.random.split(key, 7)
        s = d_model ** -0.5
        return {
            'emb': jax.random.normal(ks[0], (vocab, d_model)) * s,
            'wq': jax.random.normal(ks[1], (d_model, d_model)) * s,
            'wk': jax.random.normal(ks[2], (d_model, d_model)) * s,
            'wv': jax.random.normal(ks[3], (d_model, d_model)) * s,
            'wo': jax.random.normal(ks[4], (d_model, d_model)) * s,
            'wf': jax.random.normal(ks[5], (d_model, d_model)) * s,
            'out': jax.random.normal(ks[6], (d_model, vocab)) * s,
        }

    def forward(params, tokens):
        # tokens: (B, L) with B sharded on dp, L sharded on sp
        x = params['emb'][tokens]                       # (B, L, D)
        q = (x @ params['wq']).reshape(*x.shape[:2], n_heads, head_dim)
        k = (x @ params['wk']).reshape(*x.shape[:2], n_heads, head_dim)
        v = (x @ params['wv']).reshape(*x.shape[:2], n_heads, head_dim)
        # ring attention over the sp axis: K/V blocks rotate the ring.
        # 'striped' expects round-robin token layout (see main) and
        # balances the causal load across the ring (arXiv:2311.09431)
        attend = {'ring': ring_attention, 'striped': striped_attention,
                  'ulysses': ulysses_attention}[attn]
        att = attend(q, k, v, axis='sp', causal=True)
        att = att.reshape(*x.shape[:2], d_model)
        x = x + att @ params['wo']
        x = x + jax.nn.relu(x @ params['wf'])           # cheap mixer
        return x @ params['out']                        # (B, L, V)

    return init, forward


def needle_batch(rng, batch, seq, vocab):
    """Needle-in-a-haystack: [... maybe-NEEDLE ...... ASK] — predict
    YES/NO at the final (ASK) position iff the needle token occurred in
    the first eighth of the sequence."""
    NEEDLE, ASK, YES, NO = vocab - 4, vocab - 3, vocab - 2, vocab - 1
    toks = rng.randint(0, vocab - 4, (batch, seq))
    tgts = np.roll(toks, -1, axis=1)
    mask = np.zeros((batch, seq), np.float32)
    for b in range(batch):
        present = rng.rand() < 0.5
        if present:
            toks[b, rng.randint(0, seq // 8)] = NEEDLE
        toks[b, seq - 1] = ASK
        tgts[b, seq - 1] = YES if present else NO
        mask[b, seq - 1] = 1.0
    return toks, tgts, mask


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--dp', type=int, default=2)
    p.add_argument('--sp', type=int, default=4)
    p.add_argument('--seq', type=int, default=256)
    p.add_argument('--batch', type=int, default=16)
    p.add_argument('--vocab', type=int, default=64)
    p.add_argument('--d-model', type=int, default=64)
    p.add_argument('--heads', type=int, default=4)
    p.add_argument('--steps', type=int, default=200)
    p.add_argument('--lr', type=float, default=3e-3)
    p.add_argument('--seed', type=int, default=0)
    p.add_argument('--attn', choices=('ring', 'striped', 'ulysses'),
                   default='ring')
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    if args.attn == 'ulysses' and args.heads % args.sp:
        p.error('--attn ulysses needs --heads divisible by --sp '
                '(all_to_all moves whole heads across the axis)')
    mesh = par.make_mesh({'dp': args.dp, 'sp': args.sp})
    rng = np.random.RandomState(args.seed)
    init, forward = make_model_fns(args.vocab, args.d_model,
                                   args.heads, attn=args.attn)
    params = init(jax.random.PRNGKey(args.seed))

    data_spec = P('dp', 'sp')

    def loss_fn(params, toks, tgts, mask):
        logits = forward(params, toks).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits)
        gold = jnp.take_along_axis(logp, tgts[..., None], -1)[..., 0]
        # masked mean over recall positions only (psum'd across shards)
        num = jax.lax.psum(jnp.sum(-gold * mask), ('dp', 'sp'))
        den = jax.lax.psum(jnp.sum(mask), ('dp', 'sp'))
        return num / jnp.maximum(den, 1.0)

    opt_init, opt_update = par.data_parallel.adam_rule(lr=args.lr)

    def step(state, toks, tgts, mask):
        params, opt, t = state
        loss, grads = jax.value_and_grad(loss_fn)(params, toks, tgts, mask)
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, ('dp', 'sp')), grads)
        flat_p, tree = jax.tree_util.tree_flatten(params)
        flat_g = jax.tree_util.tree_leaves(grads)
        new_p, new_o = [], []
        for p_, g_, o_ in zip(flat_p, flat_g, opt):
            p2, o2 = opt_update(p_, g_, o_, t)
            new_p.append(p2)
            new_o.append(o2)
        return (jax.tree_util.tree_unflatten(tree, new_p), tuple(new_o),
                t + 1), loss

    sharded_step = jax.jit(shard_map(
        step, mesh=mesh.mesh,
        in_specs=((P(), P(), P()), data_spec, data_spec, data_spec),
        out_specs=((P(), P(), P()), P()), check_vma=False))
    state = (params,
             tuple(opt_init(p_) for p_ in
                   jax.tree_util.tree_leaves(params)),
             jnp.zeros((), jnp.int32))

    uniform = np.log(2.0)   # YES/NO at the answer position
    if args.attn == 'striped':
        # host-side stripe_layout permutation: position t'*sp + s moves
        # to shard s slot t' (matches parallel.stripe_layout)
        stripe_order = np.concatenate([np.arange(s, args.seq, args.sp)
                                       for s in range(args.sp)])
    first = last = None
    for i in range(args.steps):
        toks, tgts, mask = needle_batch(rng, args.batch, args.seq,
                                        args.vocab)
        if args.attn == 'striped':
            toks, tgts, mask = (toks[:, stripe_order], tgts[:, stripe_order],
                                mask[:, stripe_order])
        state, loss = sharded_step(state, jnp.asarray(toks),
                                   jnp.asarray(tgts), jnp.asarray(mask))
        loss = float(loss)
        if first is None:
            first = loss
        last = loss
        if i % 5 == 0:
            logging.info('step %d needle-loss %.3f (uniform %.3f)', i,
                         loss, uniform)
    logging.info('needle loss %.3f -> %.3f over seq=%d sharded sp=%d',
                 first, last, args.seq, args.sp)
    assert last < 0.7 * uniform, \
        'long-range detection did not learn: %.3f vs uniform %.3f' % (
            last, uniform)
    print('long-context ring-attention training ok: %.3f -> %.3f'
          % (first, last))


if __name__ == '__main__':
    main()
