"""Multi-host data-parallel training over jax.distributed.

The TPU-native replacement for the reference's dist_sync parameter-
server example (example/image-classification with kvstore='dist_sync'):
every host joins one SPMD job, the batch is sharded over a global
``dp`` mesh, and the gradient psum rides the DCN/ICI collectives that
pjit inserts — no servers.

Run W processes on one machine (or one per host with the env set):

    python tools/launch.py -n 2 --num-servers 0 \
        python examples/parallel/train_multihost.py

Each worker prints its rank's view; all ranks hold identical weights.
"""
import argparse
import os
import sys

import jax
if os.environ.get('MXTPU_EXAMPLE_CPU', '1') == '1':
    jax.config.update('jax_platforms', 'cpu')

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

from mxnet_tpu import parallel as par  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--steps', type=int, default=30)
    ap.add_argument('--batch-per-host', type=int, default=32)
    ap.add_argument('--lr', type=float, default=0.1)
    args = ap.parse_args()

    par.init_multihost()        # no-op single-process; env-driven under launch.py
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.experimental import multihost_utils

    rank, n = par.process_index(), par.process_count()
    mesh = par.global_mesh({'dp': -1})

    # toy regression: each host holds its own shard of the global batch
    rng = np.random.RandomState(1000 + rank)
    w_true = np.linspace(-1, 1, 8).astype(np.float32)
    X = rng.randn(args.batch_per_host, 8).astype(np.float32)
    Y = (X @ w_true).astype(np.float32)

    gX = multihost_utils.host_local_array_to_global_array(
        X, mesh, P('dp', None))
    gY = multihost_utils.host_local_array_to_global_array(
        Y, mesh, P('dp'))

    w = jnp.zeros((8,), jnp.float32)

    def step(w, x, y):
        def loss_fn(w):
            return jnp.mean((x @ w - y) ** 2)
        l, g = jax.value_and_grad(loss_fn)(w)
        return l, w - args.lr * g

    jstep = jax.jit(step,
                    in_shardings=(NamedSharding(mesh, P()),
                                  NamedSharding(mesh, P('dp', None)),
                                  NamedSharding(mesh, P('dp'))),
                    out_shardings=NamedSharding(mesh, P()))
    with mesh:
        for i in range(args.steps):
            loss, w = jstep(w, gX, gY)
    final = float(np.asarray(loss))
    err = float(np.abs(np.asarray(w) - w_true).max())
    print('rank %d/%d: loss=%.5f max|w-w*|=%.4f MULTIHOST_TRAIN_OK'
          % (rank, n, final, err), flush=True)
    assert err < 0.2, 'did not converge'


if __name__ == '__main__':
    main()
