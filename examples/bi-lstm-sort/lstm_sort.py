"""Sorting with a bidirectional LSTM — reference example/bi-lstm-sort/
lstm_sort.py: read a sequence of tokens and emit the same tokens in
sorted order, one output per position, trained with per-step softmax.
The bidirectional encoding is what makes position-wise sorting
learnable (each step must see the whole sequence).

    python lstm_sort.py --epochs 20
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn, rnn

VOCAB = 20
SEQ = 6


class SortNet(gluon.Block):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.embed = nn.Embedding(VOCAB, 16)
            self.lstm = rnn.LSTM(64, num_layers=2, bidirectional=True)
            self.out = nn.Dense(VOCAB, flatten=False)

    def forward(self, x):          # (T, N) int tokens
        h = self.lstm(self.embed(x))
        return self.out(h)         # (T, N, VOCAB)


def batches(rng, n):
    x = rng.randint(0, VOCAB, size=(n, SEQ))
    y = np.sort(x, axis=1)
    return x.astype(np.float32), y.astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=20)
    ap.add_argument('--samples', type=int, default=2048)
    ap.add_argument('--batch-size', type=int, default=128)
    ap.add_argument('--lr', type=float, default=5e-3)
    ap.add_argument('--min-acc', type=float, default=0.9,
                    help='per-position accuracy floor on held-out data')
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(3)

    rng = np.random.RandomState(8)
    xtr, ytr = batches(rng, args.samples)
    xte, yte = batches(rng, args.samples // 8)

    net = SortNet()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        perm = rng.permutation(len(xtr))
        tot = 0.0
        for i in range(0, len(xtr), args.batch_size):
            idx = perm[i:i + args.batch_size]
            data = mx.nd.array(xtr[idx].T)          # (T, N)
            lab = mx.nd.array(ytr[idx].T)           # (T, N)
            with autograd.record():
                logits = net(data)                  # (T, N, V)
                loss = loss_fn(logits.reshape((-1, VOCAB)),
                               lab.reshape((-1,)))
            loss.backward()
            trainer.step(len(idx))
            tot += float(loss.mean().asscalar()) * len(idx)
        logging.info('epoch %d loss %.4f', epoch, tot / len(xtr))

    pred = net(mx.nd.array(xte.T)).asnumpy().argmax(axis=-1)   # (T, N)
    acc = float((pred.T == yte).mean())
    logging.info('per-position sort accuracy %.3f', acc)
    assert acc >= args.min_acc, 'sorting failed: %.3f' % acc
    print('lstm_sort: acc=%.3f' % acc)


if __name__ == '__main__':
    main()
