"""Super-resolution CNN — reference example/gluon/super_resolution.py
(ESPCN-style): conv stack + sub-pixel upsampling, trained to 2x-upscale
images, evaluated by PSNR against bicubic-free baseline.

Hermetic: images are band-limited synthetic textures (random low
frequency Fourier modes) so 2x upscaling is learnable exactly.

    python super_resolution.py --epochs 20
"""
import argparse
import logging
import math
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.gluon import nn

UP = 2
HI = 32
LO = HI // UP


class SuperRes(gluon.Block):
    """Conv features -> UP^2 channels -> pixel shuffle (reshape form)."""

    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.c1 = nn.Conv2D(32, 5, padding=2, activation='relu')
            self.c2 = nn.Conv2D(16, 3, padding=1, activation='relu')
            self.c3 = nn.Conv2D(UP * UP, 3, padding=1)

    def forward(self, x):
        y = self.c3(self.c2(self.c1(x)))          # (B, UP*UP, LO, LO)
        B = y.shape[0]
        # sub-pixel shuffle: (B, r^2, H, W) -> (B, 1, H*r, W*r)
        y = y.reshape((B, UP, UP, LO, LO))
        y = y.transpose((0, 3, 1, 4, 2))          # B, H, r, W, r
        return y.reshape((B, 1, LO * UP, LO * UP))


def textures(rng, n):
    """Band-limited random textures: exact 2x downsample/upsample pair."""
    ky, kx = np.meshgrid(np.fft.fftfreq(HI), np.fft.fftfreq(HI),
                         indexing='ij')
    keep = (np.abs(ky) < 0.2) & (np.abs(kx) < 0.2)
    imgs = []
    for _ in range(n):
        spec = (rng.randn(HI, HI) + 1j * rng.randn(HI, HI)) * keep
        img = np.real(np.fft.ifft2(spec))
        img = (img - img.min()) / (np.ptp(img) + 1e-8)
        imgs.append(img.astype(np.float32))
    hi = np.stack(imgs)[:, None]                  # (N, 1, HI, HI)
    lo = hi[:, :, ::UP, ::UP]                     # decimation
    return lo, hi


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=20)
    p.add_argument('--batch-size', type=int, default=16)
    p.add_argument('--samples', type=int, default=128)
    p.add_argument('--lr', type=float, default=3e-3)
    p.add_argument('--seed', type=int, default=0)
    p.add_argument('--min-psnr', type=float, default=22.0)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    lo, hi = textures(rng, args.samples)
    vlo, vhi = textures(rng, 32)
    net = SuperRes()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})
    l2 = gluon.loss.L2Loss()

    n = args.samples
    for epoch in range(args.epochs):
        perm = rng.permutation(n)
        tot = 0.0
        for s in range(0, n, args.batch_size):
            idx = perm[s:s + args.batch_size]
            x = mx.nd.array(lo[idx])
            y = mx.nd.array(hi[idx])
            with autograd.record():
                loss = l2(net(x), y).mean()
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        out = net(mx.nd.array(vlo)).asnumpy()
        mse = float(np.mean((out - vhi) ** 2))
        psnr = 10 * math.log10(1.0 / max(mse, 1e-10))
        logging.info('epoch %d train-loss %.5f val PSNR %.1f dB', epoch,
                     tot, psnr)
    assert psnr > args.min_psnr, 'PSNR too low: %.1f' % psnr
    print('super_resolution ok: %.1f dB' % psnr)


if __name__ == '__main__':
    main()
