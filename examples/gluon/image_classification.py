"""Gluon imperative/hybrid training — BASELINE config #3.

Mirrors example/gluon/image_classification.py in the reference: a
model_zoo network (ResNet-v2 et al), `hybridize()` to compile the whole
forward+backward to one XLA computation, gluon Trainer + autograd.
Synthetic dataset keeps the run hermetic.

    python image_classification.py --model resnet18_v2 --epochs 2
"""
import argparse
import logging
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.model_zoo import vision


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='resnet18_v2')
    parser.add_argument('--epochs', type=int, default=2)
    parser.add_argument('--batch-size', type=int, default=32)
    parser.add_argument('--image-size', type=int, default=32)
    parser.add_argument('--classes', type=int, default=10)
    parser.add_argument('--samples', type=int, default=512)
    parser.add_argument('--lr', type=float, default=0.05)
    parser.add_argument('--no-hybridize', action='store_true')
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    # synthetic, class-separable image set
    rng = np.random.RandomState(0)
    protos = rng.rand(args.classes, 3, args.image_size, args.image_size)
    labels = rng.randint(0, args.classes, args.samples)
    images = (protos[labels] +
              0.2 * rng.randn(args.samples, 3, args.image_size,
                              args.image_size)).astype('float32')
    data = mx.io.NDArrayIter(images, labels.astype('float32'),
                             batch_size=args.batch_size, shuffle=True)

    net = vision.get_model(args.model, classes=args.classes)
    net.initialize(mx.init.Xavier(magnitude=2))
    if not args.no_hybridize:
        net.hybridize()

    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': args.lr, 'momentum': 0.9,
                             'wd': 1e-4})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    for epoch in range(args.epochs):
        data.reset()
        metric.reset()
        tic = time.time()
        n = 0
        for batch in data:
            x, y = batch.data[0], batch.label[0]
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(args.batch_size)
            metric.update([y], [out])
            n += args.batch_size
        name, acc = metric.get()
        logging.info('epoch %d: %s=%.4f (%.1f samples/s)', epoch, name, acc,
                     n / (time.time() - tic))
    return metric.get()


if __name__ == '__main__':
    main()
