"""Actor-critic on CartPole — reference example/gluon/actor_critic.py.

Same algorithm (shared trunk, policy + value heads, discounted-return
advantage, policy-gradient + L1 value loss per episode); the gym
dependency is replaced by an in-file CartPole implementation of the
standard cart-pole dynamics so the run is hermetic. The episode loss is
computed in ONE recorded batched forward over the episode's states
(same math as the reference's per-step accumulation, XLA-friendly).

    python actor_critic.py --episodes 120
"""
import argparse
import logging
import math
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.gluon import nn


class CartPole:
    """Classic cart-pole balancing dynamics (Barto/Sutton/Anderson '83)."""

    GRAV, MCART, MPOLE, LEN, FORCE, TAU = 9.8, 1.0, 0.1, 0.5, 10.0, 0.02
    X_LIM, THETA_LIM = 2.4, 12 * math.pi / 180

    def __init__(self, rng):
        self.rng = rng
        self.state = None

    def reset(self):
        self.state = self.rng.uniform(-0.05, 0.05, 4)
        return self.state.copy()

    def step(self, action):
        x, x_dot, th, th_dot = self.state
        force = self.FORCE if action == 1 else -self.FORCE
        mtot = self.MCART + self.MPOLE
        pml = self.MPOLE * self.LEN
        costh, sinth = math.cos(th), math.sin(th)
        tmp = (force + pml * th_dot ** 2 * sinth) / mtot
        th_acc = (self.GRAV * sinth - costh * tmp) / (
            self.LEN * (4.0 / 3.0 - self.MPOLE * costh ** 2 / mtot))
        x_acc = tmp - pml * th_acc * costh / mtot
        x += self.TAU * x_dot
        x_dot += self.TAU * x_acc
        th += self.TAU * th_dot
        th_dot += self.TAU * th_acc
        self.state = np.array([x, x_dot, th, th_dot])
        done = (abs(x) > self.X_LIM or abs(th) > self.THETA_LIM)
        return self.state.copy(), 1.0, done


class ActorCritic(gluon.Block):
    def __init__(self, n_actions=2, hidden=64, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.dense = nn.Dense(hidden, activation='relu')
            self.action_head = nn.Dense(n_actions)
            self.value_head = nn.Dense(1)

    def forward(self, x):
        h = self.dense(x)
        return self.action_head(h), self.value_head(h)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--episodes', type=int, default=120)
    parser.add_argument('--max-steps', type=int, default=200)
    parser.add_argument('--gamma', type=float, default=0.99)
    parser.add_argument('--lr', type=float, default=3e-2)
    parser.add_argument('--seed', type=int, default=0)
    parser.add_argument('--target', type=float, default=40.0,
                        help='required mean episode length over the last 20')
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    env = CartPole(rng)
    net = ActorCritic()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})
    l1 = gluon.loss.L1Loss()

    lengths = []
    for ep in range(args.episodes):
        # --- rollout (no tape): sample actions from the current policy
        state = env.reset()
        states, actions, rewards = [], [], []
        for t in range(args.max_steps):
            states.append(state.astype(np.float32))
            logits, _ = net(mx.nd.array(state[None].astype(np.float32)))
            prob = mx.nd.softmax(logits)[0].asnumpy()
            action = int(rng.choice(2, p=prob / prob.sum()))
            actions.append(action)
            state, r, done = env.step(action)
            rewards.append(r)
            if done:
                break
        # discounted returns, normalized
        R, returns = 0.0, []
        for r in reversed(rewards):
            R = r + args.gamma * R
            returns.append(R)
        returns = np.asarray(returns[::-1], np.float32)
        returns = (returns - returns.mean()) / (returns.std() + 1e-6)
        # --- one recorded batched forward for the whole episode
        T = len(states)
        s_nd = mx.nd.array(np.stack(states))
        ret_nd = mx.nd.array(returns.reshape(T, 1))
        with autograd.record():
            logits, values = net(s_nd)
            logp_all = mx.nd.log_softmax(logits)
            logp = mx.nd.pick(logp_all, mx.nd.array(
                np.asarray(actions, np.float32)), axis=1)
            adv = returns - values.asnumpy().ravel()
            pg = -(logp * mx.nd.array(adv)).sum()
            vl = l1(values, ret_nd).sum()
            loss = pg + vl
        loss.backward()
        trainer.step(1)
        lengths.append(len(rewards))
        if (ep + 1) % 20 == 0:
            logging.info('episode %d: mean length (last 20) %.1f', ep + 1,
                         np.mean(lengths[-20:]))
    final = float(np.mean(lengths[-20:]))
    first = float(np.mean(lengths[:20]))
    logging.info('episode length %.1f -> %.1f', first, final)
    assert final > args.target, 'did not learn: %.1f' % final
    print('actor_critic ok: %.1f -> %.1f' % (first, final))


if __name__ == '__main__':
    main()
