"""Word-level language model — reference example/gluon/word_language_model.

Embedding -> multi-layer LSTM -> decoder with OPTIONAL weight tying
(decoder shares the embedding matrix), truncated-BPTT training with
hidden-state carry and gradient clipping — the reference's training
loop shape. Corpus: a synthetic second-order Markov language, so the
model has real structure to learn and perplexity has a known floor.

    python word_language_model.py --epochs 8 --tied
"""
import argparse
import logging
import math
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.gluon import nn, rnn


class RNNModel(gluon.Block):
    def __init__(self, vocab, embed, hidden, layers, tied=False, **kw):
        super().__init__(**kw)
        self.tied = tied
        with self.name_scope():
            self.embedding = nn.Embedding(vocab, embed)
            self.lstm = rnn.LSTM(hidden, num_layers=layers,
                                 input_size=embed)
            if tied:
                assert embed == hidden, 'tying needs embed == hidden'
                self.decoder = nn.Dense(vocab, flatten=False,
                                        params=self.embedding.params)
            else:
                self.decoder = nn.Dense(vocab, flatten=False)

    def forward(self, inputs, state):
        emb = self.embedding(inputs)               # (T, B, E)
        out, state = self.lstm(emb, state)         # (T, B, H)
        return self.decoder(out), state

    def begin_state(self, batch_size):
        return self.lstm.begin_state(batch_size=batch_size)


def markov_corpus(n_tokens, vocab, seed=0):
    """Second-order Markov chain with sparse transitions: entropy well
    below log(vocab), so an LSTM that uses context wins clearly."""
    rng = np.random.RandomState(seed)
    nxt = rng.randint(0, vocab, (vocab, vocab, 3))  # 3 choices per bigram
    toks = [0, 1]
    for _ in range(n_tokens - 2):
        a, b = toks[-2], toks[-1]
        toks.append(int(nxt[a, b, rng.randint(3)]))
    return np.asarray(toks, np.int32)


def batchify(data, batch_size):
    n = len(data) // batch_size
    return data[:n * batch_size].reshape(batch_size, n).T  # (T, B)


def detach(state):
    return [s.detach() for s in state] if isinstance(state, (list, tuple)) \
        else state.detach()


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=8)
    p.add_argument('--batch-size', type=int, default=16)
    p.add_argument('--bptt', type=int, default=16)
    p.add_argument('--vocab', type=int, default=40)
    p.add_argument('--embed', type=int, default=64)
    p.add_argument('--hidden', type=int, default=64)
    p.add_argument('--layers', type=int, default=2)
    p.add_argument('--tokens', type=int, default=12000)
    p.add_argument('--lr', type=float, default=0.01)
    p.add_argument('--clip', type=float, default=1.0)
    p.add_argument('--tied', action='store_true')
    p.add_argument('--seed', type=int, default=0)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)

    data = batchify(markov_corpus(args.tokens, args.vocab, args.seed),
                    args.batch_size)
    model = RNNModel(args.vocab, args.embed, args.hidden, args.layers,
                     tied=args.tied)
    model.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(model.collect_params(), 'adam',
                            {'learning_rate': args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    uniform_ppl = float(args.vocab)
    first_ppl = last_ppl = None
    for epoch in range(args.epochs):
        total_loss, total_cnt = 0.0, 0
        state = model.begin_state(args.batch_size)
        for i in range(0, data.shape[0] - 1, args.bptt):
            # clamp the final window (reference example's shape)
            L = min(args.bptt, data.shape[0] - 1 - i)
            if L < 2:
                break
            x = mx.nd.array(data[i:i + L])
            y = mx.nd.array(data[i + 1:i + 1 + L])
            state = detach(state)   # truncate BPTT at the window edge
            with autograd.record():
                out, state = model(x, state)
                loss = loss_fn(out.reshape((-1, args.vocab)),
                               y.reshape((-1,))).mean()
            loss.backward()
            # global grad-norm clipping (reference clip_global_norm)
            grads = [p_.grad() for p_ in model.collect_params().values()
                     if p_.grad_req != 'null']
            gluon.utils.clip_global_norm(grads, args.clip)
            trainer.step(1)
            total_loss += float(loss.asnumpy()) * x.shape[0]
            total_cnt += x.shape[0]
        ppl = math.exp(total_loss / total_cnt)
        if first_ppl is None:
            first_ppl = ppl
        last_ppl = ppl
        logging.info('epoch %d perplexity %.1f (uniform %.0f)', epoch,
                     ppl, uniform_ppl)
    assert last_ppl < 0.5 * uniform_ppl, \
        'LM did not learn: ppl %.1f vs uniform %.0f' % (last_ppl,
                                                        uniform_ppl)
    print('word_language_model ok: ppl %.1f -> %.1f%s'
          % (first_ppl, last_ppl, ' (tied)' if args.tied else ''))


if __name__ == '__main__':
    main()
