"""DCGAN on synthetic data — reference example/gluon/dcgan.py.

Generator: Conv2DTranspose stack from a latent vector to a 32x32
image; discriminator: strided Conv2D stack. Adversarial training with
SoftmaxCrossEntropy on real/fake logits, both nets through gluon
autograd. Hermetic: "real" images are structured synthetic samples
(gaussian blobs), so the run asserts the adversarial dynamics — the
discriminator beats chance and the generator keeps fooling it at a
healthy rate — rather than image quality.

    python dcgan.py --epochs 2
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.gluon import nn

IMG = 32


def build_generator(nz, ngf=32):
    net = nn.HybridSequential(prefix='gen_')
    with net.name_scope():
        net.add(nn.Conv2DTranspose(ngf * 4, 4, 1, 0, use_bias=False))
        net.add(nn.BatchNorm())
        net.add(nn.Activation('relu'))                    # 4x4
        net.add(nn.Conv2DTranspose(ngf * 2, 4, 2, 1, use_bias=False))
        net.add(nn.BatchNorm())
        net.add(nn.Activation('relu'))                    # 8x8
        net.add(nn.Conv2DTranspose(ngf, 4, 2, 1, use_bias=False))
        net.add(nn.BatchNorm())
        net.add(nn.Activation('relu'))                    # 16x16
        net.add(nn.Conv2DTranspose(1, 4, 2, 1, use_bias=False))
        net.add(nn.Activation('tanh'))                    # 32x32
    return net


def build_discriminator(ndf=32):
    net = nn.HybridSequential(prefix='disc_')
    with net.name_scope():
        net.add(nn.Conv2D(ndf, 4, 2, 1, use_bias=False))
        net.add(nn.LeakyReLU(0.2))                        # 16x16
        net.add(nn.Conv2D(ndf * 2, 4, 2, 1, use_bias=False))
        net.add(nn.BatchNorm())
        net.add(nn.LeakyReLU(0.2))                        # 8x8
        net.add(nn.Conv2D(ndf * 4, 4, 2, 1, use_bias=False))
        net.add(nn.BatchNorm())
        net.add(nn.LeakyReLU(0.2))                        # 4x4
        net.add(nn.Conv2D(2, 4, 1, 0, use_bias=False))    # logits
        net.add(nn.Flatten())
    return net


def real_batch(rng, n):
    """Structured 'real' data: a gaussian blob at a random position."""
    imgs = np.zeros((n, 1, IMG, IMG), np.float32)
    ys, xs = np.mgrid[0:IMG, 0:IMG]
    for i in range(n):
        cy, cx = rng.uniform(8, IMG - 8, 2)
        s = rng.uniform(2.0, 4.0)
        imgs[i, 0] = np.exp(-((ys - cy) ** 2 + (xs - cx) ** 2) / (2 * s * s))
    return imgs * 2 - 1          # tanh range


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--epochs', type=int, default=2)
    parser.add_argument('--batches', type=int, default=20)
    parser.add_argument('--batch-size', type=int, default=16)
    parser.add_argument('--nz', type=int, default=16)
    parser.add_argument('--lr', type=float, default=2e-4)
    parser.add_argument('--seed', type=int, default=0)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    gen = build_generator(args.nz)
    disc = build_discriminator()
    gen.initialize(mx.init.Normal(0.02))
    disc.initialize(mx.init.Normal(0.02))
    g_tr = gluon.Trainer(gen.collect_params(), 'adam',
                         {'learning_rate': args.lr, 'beta1': 0.5})
    d_tr = gluon.Trainer(disc.collect_params(), 'adam',
                         {'learning_rate': args.lr, 'beta1': 0.5})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    bs = args.batch_size
    real_y = mx.nd.ones((bs,))
    fake_y = mx.nd.zeros((bs,))
    fooled_rate = 0.0
    for epoch in range(args.epochs):
        d_correct = d_total = fooled = fake_total = 0
        for it in range(args.batches):
            real = mx.nd.array(real_batch(rng, bs))
            z = mx.nd.array(rng.randn(bs, args.nz, 1, 1).astype(np.float32))
            fake = gen(z)
            # --- discriminator step ---
            with autograd.record():
                out_real = disc(real)
                out_fake = disc(fake.detach())
                d_loss = loss_fn(out_real, real_y) + loss_fn(out_fake, fake_y)
            d_loss.backward()
            d_tr.step(bs)
            pred_r = out_real.asnumpy().argmax(1)
            pred_f = out_fake.asnumpy().argmax(1)
            d_correct += int((pred_r == 1).sum() + (pred_f == 0).sum())
            d_total += 2 * bs
            # --- generator step ---
            with autograd.record():
                out = disc(gen(z))
                g_loss = loss_fn(out, real_y)
            g_loss.backward()
            g_tr.step(bs)
            fooled += int((out.asnumpy().argmax(1) == 1).sum())
            fake_total += bs
        d_acc = d_correct / d_total
        fooled_rate = fooled / fake_total
        logging.info('epoch %d: D acc %.3f, G fooled %.3f', epoch, d_acc,
                     fooled_rate)
    # adversarial sanity: D beats chance AND G still fools it (a
    # collapsed generator drives the fooled rate to ~0)
    assert d_acc > 0.6, 'discriminator never learned (%.3f)' % d_acc
    assert fooled_rate > 0.3, 'generator collapsed (%.3f)' % fooled_rate
    print('dcgan ok: D acc %.3f, G fooled %.3f' % (d_acc, fooled_rate))


if __name__ == '__main__':
    main()
