"""Sparse linear classification — reference example/sparse/
linear_classification.py: logistic regression over high-dimensional
sparse features fed by LibSVMIter (CSR batches), weights updated through
the transposed sparse dot. Hermetic: a synthetic bag-of-words-style
libsvm file (few active features per sample, labels from a sparse
ground-truth weight vector) is generated on the fly.

    python linear_classification.py --epochs 12
"""
import argparse
import logging
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx

NFEAT = 400
NNZ = 12  # active features per sample


def write_libsvm(path, rng, n, w_true):
    with open(path, 'w') as f:
        for _ in range(n):
            cols = np.sort(rng.choice(NFEAT, NNZ, replace=False))
            vals = rng.rand(NNZ).astype(np.float32) + 0.5
            y = 1 if vals @ w_true[cols] > 0 else 0
            f.write('%d %s\n' % (y, ' '.join(
                '%d:%.4f' % (c, v) for c, v in zip(cols, vals))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=15)
    ap.add_argument('--samples', type=int, default=4096)
    ap.add_argument('--batch-size', type=int, default=128)
    ap.add_argument('--lr', type=float, default=1.0)
    ap.add_argument('--min-acc', type=float, default=0.9)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(12)
    w_true = rng.randn(NFEAT).astype(np.float32)
    tmp = tempfile.mkdtemp()
    train_svm = os.path.join(tmp, 'train.libsvm')
    test_svm = os.path.join(tmp, 'test.libsvm')
    write_libsvm(train_svm, rng, args.samples, w_true)
    write_libsvm(test_svm, rng, args.samples // 4, w_true)

    train = mx.io.LibSVMIter(data_libsvm=train_svm, data_shape=(NFEAT,),
                             batch_size=args.batch_size)

    w = mx.nd.zeros((NFEAT, 1))
    b = mx.nd.zeros((1,))
    for epoch in range(args.epochs):
        train.reset()
        tot, seen = 0.0, 0
        for batch in train:
            data, lab = batch.data[0], batch.label[0]
            n = data.shape[0]
            z = mx.nd.sparse.dot(data, w).reshape((-1,)) + b
            p = 1.0 / (1.0 + (-z).exp())
            err = p - lab
            # logistic-loss gradient via the transposed sparse dot
            # (a RowSparseNDArray, like the reference's sparse grads)
            gw = (1.0 / n) * mx.nd.sparse.dot(data, err.reshape((-1, 1)),
                                              transpose_a=True)
            gb = err.mean()
            w = w - (args.lr * gw).tostype('default')
            b -= args.lr * gb
            eps = 1e-7
            tot += float((-(lab * (p + eps).log() +
                            (1 - lab) * (1 - p + eps).log())).sum().asscalar())
            seen += n
        logging.info('epoch %d logloss %.4f', epoch, tot / seen)

    test = mx.io.LibSVMIter(data_libsvm=test_svm, data_shape=(NFEAT,),
                            batch_size=args.batch_size, round_batch=False)
    correct = total = 0
    for batch in test:
        z = mx.nd.sparse.dot(batch.data[0], w).reshape((-1,)) + b
        pred = z.asnumpy() > 0
        lab = batch.label[0].asnumpy() > 0.5
        pad = getattr(batch, 'pad', 0) or 0
        n = len(lab) - pad
        correct += (pred[:n] == lab[:n]).sum()
        total += n
    acc = correct / max(total, 1)
    logging.info('test accuracy %.3f', acc)
    assert acc >= args.min_acc, 'sparse LR failed: %.3f' % acc
    print('linear_classification: acc=%.3f' % acc)


if __name__ == '__main__':
    main()
