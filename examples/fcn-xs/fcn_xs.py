"""FCN semantic segmentation — reference example/fcn-xs/fcn_xs.py +
symbol_fcnxs.py: a conv encoder downsamples, a 1x1 score head predicts
per-class maps, and a transposed convolution upsamples back to
per-pixel predictions (the FCN-32s/16s/8s pattern, compressed).
Hermetic: images contain bright geometric blobs on noise; the task is
pixel-wise blob-vs-background labeling.

    python fcn_xs.py --epochs 12
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

NCLASS = 2
HW = 24


class FCN(gluon.Block):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.c1 = nn.Conv2D(16, 3, padding=1, activation='relu')
            self.p1 = nn.MaxPool2D(2)                      # /2
            self.c2 = nn.Conv2D(32, 3, padding=1, activation='relu')
            self.p2 = nn.MaxPool2D(2)                      # /4
            self.score = nn.Conv2D(NCLASS, 1)              # 1x1 head
            self.up = nn.Conv2DTranspose(NCLASS, 8, strides=4,
                                         padding=2)        # x4 back

    def forward(self, x):
        h = self.p2(self.c2(self.p1(self.c1(x))))
        return self.up(self.score(h))      # (N, NCLASS, HW, HW)


def blobs(rng, n):
    x = 0.3 * rng.randn(n, 1, HW, HW).astype(np.float32)
    y = np.zeros((n, HW, HW), np.float32)
    for i in range(n):
        for _ in range(rng.randint(1, 3)):
            cy, cx = rng.randint(4, HW - 4, 2)
            r = rng.randint(2, 5)
            yy, xx = np.ogrid[:HW, :HW]
            mask = (yy - cy) ** 2 + (xx - cx) ** 2 <= r * r
            x[i, 0][mask] += 2.0
            y[i][mask] = 1.0
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=12)
    ap.add_argument('--samples', type=int, default=384)
    ap.add_argument('--batch-size', type=int, default=32)
    ap.add_argument('--lr', type=float, default=2e-3)
    ap.add_argument('--min-iou', type=float, default=0.6)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(4)

    rng = np.random.RandomState(14)
    xtr, ytr = blobs(rng, args.samples)
    xte, yte = blobs(rng, args.samples // 4)

    net = FCN()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})
    # per-pixel softmax CE (reference uses SoftmaxOutput multi_output)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss(axis=1)

    for epoch in range(args.epochs):
        perm = rng.permutation(len(xtr))
        tot = 0.0
        for i in range(0, len(xtr), args.batch_size):
            idx = perm[i:i + args.batch_size]
            data, lab = mx.nd.array(xtr[idx]), mx.nd.array(ytr[idx])
            with autograd.record():
                loss = loss_fn(net(data), lab)
            loss.backward()
            trainer.step(len(idx))
            tot += float(loss.mean().asscalar()) * len(idx)
        logging.info('epoch %d loss %.4f', epoch, tot / len(xtr))

    pred = net(mx.nd.array(xte)).asnumpy().argmax(axis=1)
    inter = float(np.logical_and(pred == 1, yte == 1).sum())
    union = float(np.logical_or(pred == 1, yte == 1).sum())
    iou = inter / max(union, 1.0)
    logging.info('foreground IoU %.3f', iou)
    assert iou >= args.min_iou, 'segmentation failed: IoU %.3f' % iou
    print('fcn_xs: iou=%.3f' % iou)


if __name__ == '__main__':
    main()
