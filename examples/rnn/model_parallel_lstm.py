"""Model-parallel LSTM — layers pinned to different devices.

Reference: example/model-parallel-lstm/lstm.py (each LSTM layer lives on
its own GPU via `ctx_group`, activations hop devices between layers —
the manual model-parallelism pattern from SURVEY.md §2.3).

TPU-native: the same `AttrScope(ctx_group=...)` annotations drive the
staged executor (executor.py `_forward_staged`), which jits each device's
stage and inserts `device_put` transfers at group boundaries. On a real
pod you'd prefer the pipelined form (examples/parallel, mx.parallel
GPipe) — this example exists for parity with the reference's placement
API.

    python model_parallel_lstm.py --num-layers 4 --steps 40
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx

# the virtual 8-device CPU mesh lets this run hermetically
os.environ.setdefault('XLA_FLAGS', '--xla_force_host_platform_device_count=8')


def build_symbol(num_layers, seq_len, num_hidden, num_embed, vocab):
    """Unrolled LSTM; layer i is annotated ctx_group='layer%d' % i."""
    data = mx.sym.Variable('data')
    label = mx.sym.Variable('softmax_label')
    with mx.AttrScope(ctx_group='layer0'):
        hidden = mx.sym.Embedding(data=data, input_dim=vocab,
                                  output_dim=num_embed, name='embed')
    for i in range(num_layers):
        with mx.AttrScope(ctx_group='layer%d' % i):
            cell = mx.rnn.LSTMCell(num_hidden=num_hidden,
                                   prefix='lstm_l%d_' % i)
            outputs, _ = cell.unroll(seq_len, inputs=hidden,
                                     merge_outputs=True, layout='NTC')
            hidden = outputs
    with mx.AttrScope(ctx_group='layer%d' % (num_layers - 1)):
        pred = mx.sym.Reshape(hidden, shape=(-1, num_hidden))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=vocab,
                                     name='pred')
        label_flat = mx.sym.Reshape(label, shape=(-1,))
        out = mx.sym.SoftmaxOutput(data=pred, label=label_flat,
                                   normalization='batch', name='softmax')
    return out


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--num-layers', type=int, default=4)
    parser.add_argument('--seq-len', type=int, default=16)
    parser.add_argument('--batch-size', type=int, default=16)
    parser.add_argument('--num-hidden', type=int, default=64)
    parser.add_argument('--num-embed', type=int, default=32)
    parser.add_argument('--vocab', type=int, default=50)
    parser.add_argument('--steps', type=int, default=40)
    parser.add_argument('--lr', type=float, default=0.01)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    sym = build_symbol(args.num_layers, args.seq_len, args.num_hidden,
                       args.num_embed, args.vocab)

    # one context per layer group (cycling over available devices)
    n_dev = mx.context.num_devices() if hasattr(mx.context, 'num_devices') \
        else 8
    group2ctx = {'layer%d' % i: mx.cpu(i % n_dev)
                 for i in range(args.num_layers)}

    # synthetic Markov data (same learnable structure as lstm_bucketing)
    rng = np.random.RandomState(0)
    trans = np.random.RandomState(42).dirichlet(
        np.ones(args.vocab) * 0.02, size=args.vocab)
    def batch():
        x = np.zeros((args.batch_size, args.seq_len), np.float32)
        for b in range(args.batch_size):
            x[b, 0] = rng.randint(1, args.vocab)
            for t in range(1, args.seq_len):
                x[b, t] = rng.choice(args.vocab, p=trans[int(x[b, t - 1])])
        y = np.roll(x, -1, axis=1)
        y[:, -1] = 0
        return x, y

    arg_shapes, _, _ = sym.infer_shape(
        data=(args.batch_size, args.seq_len),
        softmax_label=(args.batch_size, args.seq_len))
    arg_names = sym.list_arguments()
    init = mx.init.Xavier()
    args_map, grads_map = {}, {}
    for name, shape in zip(arg_names, arg_shapes):
        arr = mx.nd.zeros(shape)
        if name not in ('data', 'softmax_label'):
            init(mx.init.InitDesc(name), arr)
            grads_map[name] = mx.nd.zeros(shape)
        args_map[name] = arr

    exe = sym.bind(mx.cpu(0), args_map, args_grad=grads_map,
                   group2ctx=group2ctx)
    opt_state = {name: (mx.nd.zeros(g.shape), mx.nd.zeros(g.shape))
                 for name, g in grads_map.items()}

    first = last = None
    for step in range(args.steps):
        x, y = batch()
        args_map['data'][:] = x
        args_map['softmax_label'][:] = y
        exe.forward(is_train=True)
        probs = exe.outputs[0].asnumpy()
        nll = -np.log(np.maximum(
            probs[np.arange(probs.shape[0]), y.ravel().astype(int)],
            1e-8)).mean()
        exe.backward()
        for name, grad in grads_map.items():
            m, v = opt_state[name]
            mx.nd.adam_update(args_map[name], grad, m, v,
                              out=args_map[name], lr=args.lr)
        if first is None:
            first = nll
        last = nll
        if step % 10 == 0:
            logging.info('step %d nll %.4f', step, nll)
    print('model-parallel lstm: nll %.4f -> %.4f over %d layers on %d ctxs'
          % (first, last, args.num_layers, len(set(str(c) for c in group2ctx.values()))))
    assert last < first * 0.8, 'did not learn'
    return last


if __name__ == '__main__':
    main()
