"""LSTM language model with BucketingModule — BASELINE config #4.

Mirrors example/rnn/lstm_bucketing.py in the reference: variable-length
sentences bucketed by length (SURVEY.md §5.7), one Module per bucket
sharing the master parameters, Perplexity metric. Uses synthetic
sentences when no PTB files are present (zero-egress hermetic run).

    python lstm_bucketing.py --num-epochs 3
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx

BUCKETS = [8, 16, 24, 32]


def synthetic_sentences(n, vocab, seed=0):
    """Markov-chain sentences so the LM has learnable structure."""
    rng = np.random.RandomState(seed)
    trans = rng.dirichlet(np.ones(vocab) * 0.1, size=vocab)
    out = []
    for _ in range(n):
        length = rng.randint(5, BUCKETS[-1] + 1)
        s = [rng.randint(1, vocab)]
        for _ in range(length - 1):
            s.append(int(rng.choice(vocab, p=trans[s[-1]])))
        out.append(s)
    return out


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--num-epochs', type=int, default=3)
    parser.add_argument('--batch-size', type=int, default=32)
    parser.add_argument('--num-hidden', type=int, default=100)
    parser.add_argument('--num-embed', type=int, default=64)
    parser.add_argument('--num-layers', type=int, default=2)
    parser.add_argument('--vocab', type=int, default=100)
    parser.add_argument('--lr', type=float, default=0.1)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    train_iter = mx.rnn.BucketSentenceIter(
        synthetic_sentences(2000, args.vocab), args.batch_size,
        buckets=BUCKETS, invalid_label=0)

    stack = mx.rnn.SequentialRNNCell()
    for i in range(args.num_layers):
        stack.add(mx.rnn.LSTMCell(num_hidden=args.num_hidden,
                                  prefix='lstm_l%d_' % i))

    def sym_gen(seq_len):
        data = mx.sym.Variable('data')
        label = mx.sym.Variable('softmax_label')
        embed = mx.sym.Embedding(data=data, input_dim=args.vocab,
                                 output_dim=args.num_embed, name='embed')
        stack.reset()
        outputs, states = stack.unroll(seq_len, inputs=embed,
                                       merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=args.vocab,
                                     name='pred')
        label = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(data=pred, label=label, name='softmax')
        return pred, ('data',), ('softmax_label',)

    model = mx.mod.BucketingModule(
        sym_gen=sym_gen, default_bucket_key=train_iter.default_bucket_key,
        context=mx.current_context())

    model.fit(train_iter, eval_metric=mx.metric.Perplexity(ignore_label=None),
              optimizer='sgd',
              optimizer_params={'learning_rate': args.lr, 'momentum': 0.9},
              initializer=mx.init.Xavier(factor_type='in', magnitude=2.34),
              num_epoch=args.num_epochs,
              batch_end_callback=mx.callback.Speedometer(args.batch_size, 20))
    return model


if __name__ == '__main__':
    main()
