"""Two-stage detector (Faster-RCNN shape) — compact counterpart of the
reference's example/rcnn: an RPN trained against IoU-assigned anchor
targets, contrib.MultiProposal turning its outputs into ROIs, and an
ROIPooling head classifying each ROI — the full first- and second-stage
training path of the reference, on hermetic synthetic shapes.

Stage 1 trains the RPN (anchor cls + smooth-L1 bbox regression, the
reference rcnn/core/loader AnchorLoader assignment done in numpy);
stage 2 generates proposals with the trained RPN and trains the
ROI head. Asserts RPN proposal recall and ROI-head accuracy.

    python train_rcnn_lite.py --rpn-epochs 5 --head-epochs 20
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx

IMG = 64
STRIDE = 8
FEAT = IMG // STRIDE                  # 8x8 feature map
SCALES = (2.0, 4.0)                   # anchor sides 16 and 32 at stride 8
RATIOS = (0.5, 1.0, 2.0)
A = len(SCALES) * len(RATIOS)
NUM_CLASSES = 3                       # foreground shapes


def gen_anchors():
    """Anchor grid matching MultiProposal's generation (contrib ops)."""
    base = STRIDE / 2.0
    anchors = []
    for y in range(FEAT):
        for x in range(FEAT):
            cx, cy = x * STRIDE + base, y * STRIDE + base
            for r in RATIOS:
                for s in SCALES:
                    size = s * STRIDE
                    w = size * np.sqrt(1.0 / r)
                    h = size * np.sqrt(r)
                    anchors.append([cx - w / 2, cy - h / 2,
                                    cx + w / 2, cy + h / 2])
    return np.asarray(anchors, np.float32)          # (FEAT*FEAT*A, 4)


def iou_matrix(a, b):
    ix0 = np.maximum(a[:, None, 0], b[None, :, 0])
    iy0 = np.maximum(a[:, None, 1], b[None, :, 1])
    ix1 = np.minimum(a[:, None, 2], b[None, :, 2])
    iy1 = np.minimum(a[:, None, 3], b[None, :, 3])
    inter = np.clip(ix1 - ix0, 0, None) * np.clip(iy1 - iy0, 0, None)
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return inter / np.maximum(area_a[:, None] + area_b[None, :] - inter,
                              1e-6)


def synthetic_scene(rng):
    """One image with 1-2 axis-aligned objects of NUM_CLASSES kinds."""
    img = rng.randn(3, IMG, IMG).astype(np.float32) * 0.1
    boxes, classes = [], []
    for _ in range(rng.randint(1, 3)):
        cls = rng.randint(NUM_CLASSES)
        w, h = rng.uniform(12, 28, 2)
        x0 = rng.uniform(2, IMG - w - 2)
        y0 = rng.uniform(2, IMG - h - 2)
        xi = np.s_[int(y0):int(y0 + h), int(x0):int(x0 + w)]
        img[cls][xi] += 1.0
        boxes.append([x0, y0, x0 + w, y0 + h])
        classes.append(cls)
    return img, np.asarray(boxes, np.float32), np.asarray(classes)


def anchor_targets(anchors, gt_boxes):
    """RPN label assignment (reference rcnn AnchorLoader): IoU>0.5 or
    per-gt argmax -> positive, IoU<0.2 -> negative, else ignore (-1)."""
    iou = iou_matrix(anchors, gt_boxes)
    labels = -np.ones(len(anchors), np.float32)
    labels[iou.max(1) < 0.2] = 0
    labels[iou.max(1) > 0.5] = 1
    labels[iou.argmax(0)] = 1                       # best anchor per gt
    # bbox regression targets for positives (standard R-CNN encoding)
    tgt = np.zeros((len(anchors), 4), np.float32)
    pos = np.where(labels == 1)[0]
    g = gt_boxes[iou[pos].argmax(1)]
    aw = anchors[pos, 2] - anchors[pos, 0]
    ah = anchors[pos, 3] - anchors[pos, 1]
    acx = anchors[pos, 0] + aw / 2
    acy = anchors[pos, 1] + ah / 2
    gw = g[:, 2] - g[:, 0]
    gh = g[:, 3] - g[:, 1]
    gcx = g[:, 0] + gw / 2
    gcy = g[:, 1] + gh / 2
    tgt[pos, 0] = (gcx - acx) / aw
    tgt[pos, 1] = (gcy - acy) / ah
    tgt[pos, 2] = np.log(gw / aw)
    tgt[pos, 3] = np.log(gh / ah)
    return labels, tgt


def rpn_symbol():
    data = mx.sym.Variable('data')
    lab = mx.sym.Variable('rpn_label')              # (B, FEAT*FEAT*A)
    btgt = mx.sym.Variable('rpn_bbox_target')       # (B, A*4, F, F)
    bmask = mx.sym.Variable('rpn_bbox_mask')
    x = data
    for i, nf in enumerate([16, 32, 32]):
        x = mx.sym.Convolution(x, kernel=(3, 3), num_filter=nf,
                               stride=(2, 2), pad=(1, 1),
                               name='b%d' % i)
        x = mx.sym.Activation(x, act_type='relu')
    # x: (B, 32, 8, 8) after 3 stride-2 convs from 64 -> 8
    feat = mx.sym.Convolution(x, kernel=(3, 3), pad=(1, 1), num_filter=32,
                              name='rpn_conv')
    feat = mx.sym.Activation(feat, act_type='relu')
    cls = mx.sym.Convolution(feat, kernel=(1, 1), num_filter=2 * A,
                             name='rpn_cls')        # (B, 2A, F, F)
    bbox = mx.sym.Convolution(feat, kernel=(1, 1), num_filter=4 * A,
                              name='rpn_bbox')
    # cls loss over anchors: (B, 2A, F, F) -> (B, 2, A*F*F)
    cls_r = mx.sym.Reshape(cls, shape=(0, 2, -1))
    cls_loss = mx.sym.SoftmaxOutput(cls_r, lab, multi_output=True,
                                    use_ignore=True, ignore_label=-1,
                                    normalization='valid',
                                    name='rpn_cls_prob')
    bb_diff = bmask * (bbox - btgt)
    bb_loss = mx.sym.MakeLoss(mx.sym.smooth_l1(bb_diff, scalar=3.0),
                              grad_scale=1.0 / (FEAT * FEAT),
                              name='rpn_bbox_loss')
    return mx.sym.Group([cls_loss, bb_loss, mx.sym.BlockGrad(cls),
                         mx.sym.BlockGrad(bbox)])


def scene_batch(rng, n, anchors):
    imgs = np.zeros((n, 3, IMG, IMG), np.float32)
    labels = np.zeros((n, len(anchors)), np.float32)
    btgts = np.zeros((n, len(anchors), 4), np.float32)
    scenes = []
    for i in range(n):
        img, boxes, classes = synthetic_scene(rng)
        imgs[i] = img
        lab, tgt = anchor_targets(anchors, boxes)
        labels[i] = lab
        btgts[i] = tgt
        scenes.append((boxes, classes))
    # (B, N_anchor) cls labels where anchor index order matches the
    # (A, F, F) conv layout flattened as in cls_r: channel-major per A
    # our anchors are ordered (y, x, A); conv layout is (A, y, x)
    perm = np.arange(len(anchors)).reshape(FEAT, FEAT, A)
    perm = perm.transpose(2, 0, 1).ravel()
    labels = labels[:, perm]
    btgts = btgts[:, perm].reshape(n, A, FEAT, FEAT, 4)
    btgts = btgts.transpose(0, 1, 4, 2, 3).reshape(n, A * 4, FEAT, FEAT)
    masks = (labels.reshape(n, A, FEAT, FEAT) == 1)[:, :, None]
    masks = np.repeat(masks, 4, axis=2).reshape(n, A * 4, FEAT, FEAT)
    return imgs, labels, btgts, masks.astype(np.float32), scenes


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--rpn-epochs', type=int, default=5)
    p.add_argument('--head-epochs', type=int, default=20)
    p.add_argument('--batch-size', type=int, default=8)
    p.add_argument('--samples', type=int, default=48)
    p.add_argument('--lr', type=float, default=0.005)
    p.add_argument('--seed', type=int, default=0)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)
    anchors = gen_anchors()

    # ---------------- stage 1: RPN ----------------
    sym = rpn_symbol()
    imgs, labels, btgts, masks, scenes = scene_batch(rng, args.samples,
                                                     anchors)
    it = mx.io.NDArrayIter({'data': imgs},
                           {'rpn_label': labels, 'rpn_bbox_target': btgts,
                            'rpn_bbox_mask': masks},
                           batch_size=args.batch_size, shuffle=True)
    mod = mx.mod.Module(sym, data_names=('data',),
                        label_names=('rpn_label', 'rpn_bbox_target',
                                     'rpn_bbox_mask'))
    mod.fit(it, num_epoch=args.rpn_epochs, optimizer='adam',
            optimizer_params={'learning_rate': args.lr},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.Loss(output_names=['rpn_bbox_loss_output']))

    # ---------------- proposals from the trained RPN ----------------
    arg_p, aux_p = mod.get_params()
    test_sym = rpn_symbol()
    internals = test_sym.get_internals()
    cls_raw = internals['rpn_cls_output']
    bbox_raw = internals['rpn_bbox_output']
    cls_softmax = mx.sym.Reshape(
        mx.sym.softmax(mx.sym.Reshape(cls_raw, shape=(0, 2, -1)), axis=1),
        shape=(0, 2 * A, FEAT, FEAT))
    rois_sym = mx.sym.contrib.MultiProposal(
        cls_softmax, bbox_raw, mx.sym.Variable('im_info'),
        rpn_pre_nms_top_n=64, rpn_post_nms_top_n=16, threshold=0.7,
        rpn_min_size=4, scales=SCALES, ratios=RATIOS,
        feature_stride=STRIDE, name='proposals')
    feat_sym = internals['b2_output']  # backbone isn't needed separately
    group = mx.sym.Group([rois_sym, feat_sym])
    prop_mod = mx.mod.Module(group, data_names=('data', 'im_info'),
                             label_names=None)
    prop_mod.bind(data_shapes=[('data', (args.batch_size, 3, IMG, IMG)),
                               ('im_info', (args.batch_size, 3))],
                  for_training=False)
    prop_mod.set_params(arg_p, aux_p, allow_missing=True)

    def proposals_for(img_batch):
        im_info = np.tile([IMG, IMG, 1.0],
                          (img_batch.shape[0], 1)).astype(np.float32)
        prop_mod.forward(mx.io.DataBatch(
            [mx.nd.array(img_batch), mx.nd.array(im_info)], []),
            is_train=False)
        rois, feats = prop_mod.get_outputs()
        return rois.asnumpy(), feats.asnumpy()

    # RPN recall: fraction of gt boxes covered by a proposal IoU>0.5
    rois, _ = proposals_for(imgs[:args.batch_size])
    covered = total = 0
    for b in range(args.batch_size):
        gt = scenes[b][0]
        mine = rois[rois[:, 0] == b][:, 1:]
        total += len(gt)
        if len(mine):
            covered += (iou_matrix(gt, mine).max(1) > 0.5).sum()
    recall = covered / max(1, total)
    logging.info('RPN proposal recall@0.5 = %.2f', recall)

    # ---------------- stage 2: ROI head ----------------
    # Pool once per image group (ROIPooling has no parameters), then
    # train the classification head on pooled features at real batch
    # sizes — the reference's head also consumes pooled blobs.
    pooled_all, roi_labels = [], []
    for s in range(0, args.samples, args.batch_size):
        batch_imgs = imgs[s:s + args.batch_size]
        rois, feats = proposals_for(batch_imgs)
        keep_rois, labs = [], []
        for b in range(batch_imgs.shape[0]):
            gt_boxes, gt_cls = scenes[s + b]
            mine = rois[rois[:, 0] == b]
            if not len(mine):
                continue
            iou = iou_matrix(mine[:, 1:], gt_boxes)
            best = iou.argmax(1)
            lab = np.where(iou.max(1) > 0.5, gt_cls[best] + 1, 0)
            keep = np.concatenate([np.where(lab > 0)[0],
                                   np.where(lab == 0)[0][:4]])
            keep_rois.append(mine[keep])
            labs.append(lab[keep])
        if not keep_rois:
            continue
        keep_rois = np.concatenate(keep_rois)
        pooled = mx.nd.ROIPooling(mx.nd.array(feats),
                                  mx.nd.array(keep_rois),
                                  pooled_size=(4, 4),
                                  spatial_scale=1.0 / STRIDE)
        pooled_all.append(pooled.asnumpy())
        roi_labels.append(np.concatenate(labs))
    pooled_all = np.concatenate(pooled_all).astype(np.float32)
    roi_labels = np.concatenate(roi_labels).astype(np.float32)
    logging.info('ROI training set: %d rois (%.0f%% fg)', len(pooled_all),
                 100 * (roi_labels > 0).mean())

    feat_v = mx.sym.Variable('pooled')
    h = mx.sym.FullyConnected(mx.sym.flatten(feat_v), num_hidden=64,
                              name='h1')
    h = mx.sym.Activation(h, act_type='relu')
    h = mx.sym.FullyConnected(h, num_hidden=NUM_CLASSES + 1, name='h2')
    head = mx.sym.SoftmaxOutput(h, name='softmax')
    hmod = mx.mod.Module(head, data_names=('pooled',),
                         label_names=('softmax_label',))
    hit = mx.io.NDArrayIter({'pooled': pooled_all},
                            {'softmax_label': roi_labels}, batch_size=32,
                            shuffle=True)
    hmod.fit(hit, num_epoch=args.head_epochs, optimizer='adam',
             optimizer_params={'learning_rate': args.lr},
             initializer=mx.init.Xavier(), eval_metric='acc')
    score = dict(hmod.score(hit, 'acc'))
    logging.info('ROI head accuracy %.2f', score['accuracy'])

    assert recall > 0.5, 'RPN recall too low: %.2f' % recall
    assert score['accuracy'] > 0.7, 'head accuracy: %s' % score
    print('rcnn-lite ok: recall %.2f, head acc %.2f'
          % (recall, score['accuracy']))


if __name__ == '__main__':
    main()
