"""SSD object detection — BASELINE config #5.

Compact counterpart of the reference's example/ssd (VGG16-SSD): a conv
backbone with multi-scale heads, MultiBoxPrior anchors, MultiBoxTarget
training targets, and MultiBoxDetection NMS decode at inference — all
through the contrib ops (ops/contrib_ops.py, reference
src/operator/contrib/multibox_*.cc). Trains on synthetic box data so the
run is hermetic.

    python train_ssd.py --epochs 2
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx

IMG = 64
NUM_CLASSES = 3  # foreground classes
MAX_OBJS = 4


def conv_block(data, num_filter, name, stride=(1, 1)):
    c = mx.sym.Convolution(data=data, num_filter=num_filter, kernel=(3, 3),
                           stride=stride, pad=(1, 1), name=name + '_conv')
    b = mx.sym.BatchNorm(data=c, name=name + '_bn')
    return mx.sym.Activation(data=b, act_type='relu', name=name + '_relu')


def ssd_symbol(mode='train'):
    data = mx.sym.Variable('data')
    label = mx.sym.Variable('label')

    body = conv_block(data, 16, 'b1')
    body = mx.sym.Pooling(data=body, kernel=(2, 2), stride=(2, 2),
                          pool_type='max')          # 32x32
    body = conv_block(body, 32, 'b2')
    scale1 = mx.sym.Pooling(data=body, kernel=(2, 2), stride=(2, 2),
                            pool_type='max')        # 16x16
    scale1 = conv_block(scale1, 64, 'b3')
    scale2 = conv_block(scale1, 64, 'b4', stride=(2, 2))   # 8x8

    preds, anchors = [], []
    cfg = [(scale1, (0.2, 0.35), (1.0, 2.0, 0.5)),
           (scale2, (0.4, 0.6), (1.0, 2.0, 0.5))]
    num_anchors_per = len(cfg[0][2]) + len(cfg[0][1]) - 1
    for i, (feat, sizes, ratios) in enumerate(cfg):
        anc = mx.sym.contrib.MultiBoxPrior(feat, sizes=sizes, ratios=ratios,
                                           clip=True,
                                           name='anchors%d' % i)
        pred = mx.sym.Convolution(
            data=feat, num_filter=num_anchors_per * (NUM_CLASSES + 1 + 4),
            kernel=(3, 3), pad=(1, 1), name='pred%d' % i)
        # [B, A*(C+1+4), H, W] -> [B, H*W*A, C+1+4]
        pred = mx.sym.transpose(pred, axes=(0, 2, 3, 1))
        pred = mx.sym.Reshape(pred, shape=(0, -1, NUM_CLASSES + 1 + 4))
        preds.append(pred)
        anchors.append(mx.sym.Reshape(anc, shape=(0, -1, 4)))
    pred = mx.sym.Concat(*preds, dim=1)
    anchor = mx.sym.Concat(*anchors, dim=1)
    cls_pred = mx.sym.slice_axis(pred, axis=2, begin=0, end=NUM_CLASSES + 1)
    loc_pred = mx.sym.Reshape(
        mx.sym.slice_axis(pred, axis=2, begin=NUM_CLASSES + 1,
                          end=NUM_CLASSES + 1 + 4), shape=(0, -1))
    # MultiBoxTarget expects cls_pred as [B, C+1, A]
    cls_pred_t = mx.sym.transpose(cls_pred, axes=(0, 2, 1))

    if mode == 'train':
        loc_t, loc_m, cls_t = mx.sym.contrib.MultiBoxTarget(
            anchor, label, cls_pred_t, overlap_threshold=0.5,
            name='multibox_target')
        cls_loss = mx.sym.SoftmaxOutput(data=cls_pred_t, label=cls_t,
                                        multi_output=True,
                                        use_ignore=True, ignore_label=-1,
                                        normalization='valid',
                                        name='cls_prob')
        loc_diff = loc_m * (loc_pred - loc_t)
        loc_loss = mx.sym.MakeLoss(mx.sym.smooth_l1(loc_diff, scalar=1.0),
                                   grad_scale=1.0, name='loc_loss')
        return mx.sym.Group([cls_loss, loc_loss,
                             mx.sym.BlockGrad(cls_t, name='cls_label')])
    cls_prob = mx.sym.softmax(cls_pred_t, axis=1)
    return mx.sym.contrib.MultiBoxDetection(cls_prob, loc_pred, anchor,
                                            nms_threshold=0.5,
                                            name='detection')


def synthetic_detection_data(n, seed=0):
    """Images with colored rectangles; label [n, MAX_OBJS, 5] =
    (cls, xmin, ymin, xmax, ymax) normalized, -1 padding."""
    rng = np.random.RandomState(seed)
    images = np.zeros((n, 3, IMG, IMG), np.float32)
    labels = -np.ones((n, MAX_OBJS, 5), np.float32)
    for i in range(n):
        for j in range(rng.randint(1, MAX_OBJS + 1)):
            cls = rng.randint(0, NUM_CLASSES)
            w, h = rng.uniform(0.2, 0.5, 2)
            x0 = rng.uniform(0, 1 - w)
            y0 = rng.uniform(0, 1 - h)
            xi0, yi0 = int(x0 * IMG), int(y0 * IMG)
            xi1, yi1 = int((x0 + w) * IMG), int((y0 + h) * IMG)
            images[i, cls, yi0:yi1, xi0:xi1] = 1.0
            labels[i, j] = (cls, x0, y0, x0 + w, y0 + h)
        images[i] += 0.1 * rng.randn(3, IMG, IMG)
    return images, labels


def vendor_record_dataset(path, n, seed=0):
    """Pack the labeled set into a detection .rec (the reference's
    im2rec --pack-label format: label = [hdr_w, obj_w, rows...]), so
    training runs through the real RecordIO pipeline
    (recordio.pack_img + mx.io.ImageDetRecordIter)."""
    from mxnet_tpu import recordio
    images, labels = synthetic_detection_data(n, seed=seed)
    rec = recordio.MXRecordIO(path, 'w')
    for i in range(n):
        objs = labels[i][labels[i][:, 0] >= 0]
        packed = np.concatenate([[2.0, 5.0], objs.ravel()]).astype(
            np.float32)
        header = recordio.IRHeader(len(packed), packed, i, 0)
        # .rec stores uint8 pixels (reference im2rec convention), kept
        # CHW so the stored shape equals the iterator's data_shape; the
        # iterator rescales by 1/255
        img = (np.clip(images[i], 0.0, 1.0) * 255.0).round().astype(np.uint8)
        rec.write(recordio.pack_img(header, img, img_fmt='.raw'))
    rec.close()
    return images, labels


class _DetLabelAdapter(mx.io.DataIter):
    """Strips the packed-label header and reshapes to (B, objs, 5) —
    what MultiBoxTarget consumes (the reference's train scripts do the
    same reshape around ImageDetRecordIter)."""

    def __init__(self, inner):
        super().__init__(inner.batch_size)
        self._it = inner
        self._obj_w = inner.label_object_width

    @property
    def provide_data(self):
        return self._it.provide_data

    @property
    def provide_label(self):
        d = self._it.provide_label[0]
        b = d.shape[0]
        n_obj = (d.shape[1] - 2) // self._obj_w
        return [mx.io.DataDesc('label', (b, n_obj, self._obj_w), d.dtype)]

    def reset(self):
        self._it.reset()

    def next(self):
        batch = self._it.next()
        lab = batch.label[0].asnumpy()[:, 2:]
        lab = lab.reshape(lab.shape[0], -1, self._obj_w)
        return mx.io.DataBatch([batch.data[0]], [mx.nd.array(lab)],
                               pad=batch.pad)


def evaluate_detection(mod_train, images, labels, score_thr=0.3,
                       iou_thr=0.5):
    """Recall of ground-truth objects matched by a same-class detection
    with IoU over the threshold."""
    det_sym = ssd_symbol('test')
    det = mx.mod.Module(det_sym, data_names=('data',), label_names=None)
    det.bind(data_shapes=[('data', images.shape)], for_training=False)
    args_, auxs = mod_train.get_params()
    det.set_params(args_, auxs, allow_missing=False)
    det.forward(mx.io.DataBatch([mx.nd.array(images)], []), is_train=False)
    out = det.get_outputs()[0].asnumpy()  # (B, A, 6) id,score,4 box
    matched = total = 0
    for i in range(images.shape[0]):
        dets = out[i][(out[i, :, 0] >= 0) & (out[i, :, 1] > score_thr)]
        for obj in labels[i]:
            if obj[0] < 0:
                continue
            total += 1
            for d in dets:
                if int(d[0]) != int(obj[0]):
                    continue
                ix0, iy0 = np.maximum(d[2:4], obj[1:3])
                ix1, iy1 = np.minimum(d[4:6], obj[3:5])
                inter = max(0, ix1 - ix0) * max(0, iy1 - iy0)
                ua = ((d[4] - d[2]) * (d[5] - d[3]) +
                      (obj[3] - obj[1]) * (obj[4] - obj[2]) - inter)
                if ua > 0 and inter / ua > iou_thr:
                    matched += 1
                    break
    return matched / max(1, total)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--epochs', type=int, default=2)
    parser.add_argument('--batch-size', type=int, default=16)
    parser.add_argument('--samples', type=int, default=128)
    parser.add_argument('--lr', type=float, default=0.05)
    parser.add_argument('--rec', default=None,
                        help='path for the vendored .rec (default: '
                             'data/ssd_synth.rec next to this script)')
    parser.add_argument('--min-recall', type=float, default=-1.0,
                        help='fail unless eval recall exceeds this')
    parser.add_argument('--seed', type=int, default=0)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)

    rec_path = args.rec or os.path.join(os.path.dirname(__file__) or '.',
                                        'data', 'ssd_synth.rec')
    rec_dir = os.path.dirname(rec_path)
    if rec_dir:
        os.makedirs(rec_dir, exist_ok=True)
    vendor_record_dataset(rec_path, args.samples, seed=args.seed)
    logging.info('vendored labeled dataset: %s', rec_path)
    rec_iter = mx.io.ImageDetRecordIter(
        path_imgrec=rec_path, data_shape=(3, IMG, IMG),
        batch_size=args.batch_size, shuffle=True, scale=1.0 / 255.0,
        label_pad_width=2 + MAX_OBJS * 5)
    train = _DetLabelAdapter(rec_iter)

    net = ssd_symbol('train')
    mod = mx.mod.Module(net, label_names=('label',),
                        context=mx.current_context())
    mod.fit(train,
            eval_metric=mx.metric.Loss(output_names=['loc_loss_output']),
            optimizer='sgd',
            optimizer_params={'learning_rate': args.lr, 'momentum': 0.9,
                              'wd': 5e-4},
            initializer=mx.init.Xavier(),
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 4),
            num_epoch=args.epochs)
    # in-distribution eval (same generator, training seed): measures
    # that the full target->loss->decode machinery learns the task it
    # trained on (the reference's eval is a VOC mAP over its own train
    # distribution); NOT a held-out generalization number
    val_images, val_labels = synthetic_detection_data(64, seed=args.seed)
    recall = evaluate_detection(mod, val_images, val_labels, score_thr=0.2)
    logging.info('SSD training complete; recall@0.5IoU = %.3f', recall)
    if args.min_recall >= 0:
        assert recall > args.min_recall, \
            'recall %.3f below required %.3f' % (recall, args.min_recall)
    return mod


if __name__ == '__main__':
    main()
