"""SSD object detection — BASELINE config #5.

Compact counterpart of the reference's example/ssd (VGG16-SSD): a conv
backbone with multi-scale heads, MultiBoxPrior anchors, MultiBoxTarget
training targets, and MultiBoxDetection NMS decode at inference — all
through the contrib ops (ops/contrib_ops.py, reference
src/operator/contrib/multibox_*.cc). Trains on synthetic box data so the
run is hermetic.

    python train_ssd.py --epochs 2
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx

IMG = 64
NUM_CLASSES = 3  # foreground classes
MAX_OBJS = 4


def conv_block(data, num_filter, name, stride=(1, 1)):
    c = mx.sym.Convolution(data=data, num_filter=num_filter, kernel=(3, 3),
                           stride=stride, pad=(1, 1), name=name + '_conv')
    b = mx.sym.BatchNorm(data=c, name=name + '_bn')
    return mx.sym.Activation(data=b, act_type='relu', name=name + '_relu')


def ssd_symbol(mode='train'):
    data = mx.sym.Variable('data')
    label = mx.sym.Variable('label')

    body = conv_block(data, 16, 'b1')
    body = mx.sym.Pooling(data=body, kernel=(2, 2), stride=(2, 2),
                          pool_type='max')          # 32x32
    body = conv_block(body, 32, 'b2')
    scale1 = mx.sym.Pooling(data=body, kernel=(2, 2), stride=(2, 2),
                            pool_type='max')        # 16x16
    scale1 = conv_block(scale1, 64, 'b3')
    scale2 = conv_block(scale1, 64, 'b4', stride=(2, 2))   # 8x8

    preds, anchors = [], []
    cfg = [(scale1, (0.2, 0.35), (1.0, 2.0, 0.5)),
           (scale2, (0.4, 0.6), (1.0, 2.0, 0.5))]
    num_anchors_per = len(cfg[0][2]) + len(cfg[0][1]) - 1
    for i, (feat, sizes, ratios) in enumerate(cfg):
        anc = mx.sym.contrib.MultiBoxPrior(feat, sizes=sizes, ratios=ratios,
                                           clip=True,
                                           name='anchors%d' % i)
        pred = mx.sym.Convolution(
            data=feat, num_filter=num_anchors_per * (NUM_CLASSES + 1 + 4),
            kernel=(3, 3), pad=(1, 1), name='pred%d' % i)
        # [B, A*(C+1+4), H, W] -> [B, H*W*A, C+1+4]
        pred = mx.sym.transpose(pred, axes=(0, 2, 3, 1))
        pred = mx.sym.Reshape(pred, shape=(0, -1, NUM_CLASSES + 1 + 4))
        preds.append(pred)
        anchors.append(mx.sym.Reshape(anc, shape=(0, -1, 4)))
    pred = mx.sym.Concat(*preds, dim=1)
    anchor = mx.sym.Concat(*anchors, dim=1)
    cls_pred = mx.sym.slice_axis(pred, axis=2, begin=0, end=NUM_CLASSES + 1)
    loc_pred = mx.sym.Reshape(
        mx.sym.slice_axis(pred, axis=2, begin=NUM_CLASSES + 1,
                          end=NUM_CLASSES + 1 + 4), shape=(0, -1))
    # MultiBoxTarget expects cls_pred as [B, C+1, A]
    cls_pred_t = mx.sym.transpose(cls_pred, axes=(0, 2, 1))

    if mode == 'train':
        loc_t, loc_m, cls_t = mx.sym.contrib.MultiBoxTarget(
            anchor, label, cls_pred_t, overlap_threshold=0.5,
            name='multibox_target')
        cls_loss = mx.sym.SoftmaxOutput(data=cls_pred_t, label=cls_t,
                                        multi_output=True,
                                        use_ignore=True, ignore_label=-1,
                                        normalization='valid',
                                        name='cls_prob')
        loc_diff = loc_m * (loc_pred - loc_t)
        loc_loss = mx.sym.MakeLoss(mx.sym.smooth_l1(loc_diff, scalar=1.0),
                                   grad_scale=1.0, name='loc_loss')
        return mx.sym.Group([cls_loss, loc_loss,
                             mx.sym.BlockGrad(cls_t, name='cls_label')])
    cls_prob = mx.sym.softmax(cls_pred_t, axis=1)
    return mx.sym.contrib.MultiBoxDetection(cls_prob, loc_pred, anchor,
                                            nms_threshold=0.5,
                                            name='detection')


def synthetic_detection_data(n, seed=0):
    """Images with colored rectangles; label [n, MAX_OBJS, 5] =
    (cls, xmin, ymin, xmax, ymax) normalized, -1 padding."""
    rng = np.random.RandomState(seed)
    images = np.zeros((n, 3, IMG, IMG), np.float32)
    labels = -np.ones((n, MAX_OBJS, 5), np.float32)
    for i in range(n):
        for j in range(rng.randint(1, MAX_OBJS + 1)):
            cls = rng.randint(0, NUM_CLASSES)
            w, h = rng.uniform(0.2, 0.5, 2)
            x0 = rng.uniform(0, 1 - w)
            y0 = rng.uniform(0, 1 - h)
            xi0, yi0 = int(x0 * IMG), int(y0 * IMG)
            xi1, yi1 = int((x0 + w) * IMG), int((y0 + h) * IMG)
            images[i, cls, yi0:yi1, xi0:xi1] = 1.0
            labels[i, j] = (cls, x0, y0, x0 + w, y0 + h)
        images[i] += 0.1 * rng.randn(3, IMG, IMG)
    return images, labels


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--epochs', type=int, default=2)
    parser.add_argument('--batch-size', type=int, default=16)
    parser.add_argument('--samples', type=int, default=128)
    parser.add_argument('--lr', type=float, default=0.05)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    images, labels = synthetic_detection_data(args.samples)
    train = mx.io.NDArrayIter(images, labels, batch_size=args.batch_size,
                              shuffle=True, label_name='label')

    net = ssd_symbol('train')
    mod = mx.mod.Module(net, label_names=('label',),
                        context=mx.current_context())
    mod.fit(train,
            eval_metric=mx.metric.Loss(output_names=['loc_loss_output']),
            optimizer='sgd',
            optimizer_params={'learning_rate': args.lr, 'momentum': 0.9,
                              'wd': 5e-4},
            initializer=mx.init.Xavier(),
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 4),
            num_epoch=args.epochs)
    logging.info('SSD training complete')
    return mod


if __name__ == '__main__':
    main()
