"""Dense-Sparse-Dense training — reference example/dsd/ (Han et al.
2017): train dense, prune the smallest weights and retrain under the
sparsity mask, then remove the mask and retrain densely — the final
dense model should match or beat the first dense pass.

    python dsd.py --epochs 6
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

NCLASS = 6
DIM = 32


def blobs(rng, n, centers):
    lab = rng.randint(0, NCLASS, n)
    x = centers[lab] + 0.5 * rng.randn(n, DIM).astype(np.float32)
    return x.astype(np.float32), lab.astype(np.float32)


def train_phase(net, x, y, epochs, lr, rng, masks=None, tag=''):
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': lr, 'momentum': 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for epoch in range(epochs):
        perm = rng.permutation(len(x))
        tot = 0.0
        for i in range(0, len(x), 64):
            idx = perm[i:i + 64]
            with autograd.record():
                loss = loss_fn(net(mx.nd.array(x[idx])),
                               mx.nd.array(y[idx]))
            loss.backward()
            trainer.step(len(idx))
            if masks:
                # sparse phase: keep pruned weights at exactly zero
                for p, m in masks.items():
                    d = p.data()
                    p.set_data(d * m)
            tot += float(loss.mean().asscalar()) * len(idx)
        logging.info('%s epoch %d loss %.4f', tag, epoch, tot / len(x))


def accuracy(net, x, y):
    return float((net(mx.nd.array(x)).asnumpy().argmax(1) == y).mean())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=6)
    ap.add_argument('--samples', type=int, default=768)
    ap.add_argument('--lr', type=float, default=0.05)
    ap.add_argument('--sparsity', type=float, default=0.5)
    ap.add_argument('--min-acc', type=float, default=0.9)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(8)

    rng = np.random.RandomState(19)
    centers = rng.randn(NCLASS, DIM).astype(np.float32) * 1.5
    xtr, ytr = blobs(rng, args.samples, centers)
    xte, yte = blobs(rng, args.samples // 4, centers)

    net = nn.Sequential()
    with net.name_scope():
        net.add(nn.Dense(64, activation='relu'),
                nn.Dense(64, activation='relu'), nn.Dense(NCLASS))
    net.initialize(mx.init.Xavier())

    # phase 1: dense
    train_phase(net, xtr, ytr, args.epochs, args.lr, rng, tag='dense')
    acc_dense = accuracy(net, xte, yte)

    # prune: zero the smallest |w| per weight matrix
    masks = {}
    pruned = total = 0
    for name, p in net.collect_params().items():
        if not name.endswith('weight'):
            continue
        w = p.data().asnumpy()
        thresh = np.quantile(np.abs(w), args.sparsity)
        m = (np.abs(w) > thresh).astype(np.float32)
        masks[p] = mx.nd.array(m)
        p.set_data(p.data() * masks[p])
        pruned += int((m == 0).sum())
        total += m.size
    logging.info('pruned %d/%d weights (%.0f%%)', pruned, total,
                 100 * pruned / total)
    acc_pruned = accuracy(net, xte, yte)

    # phase 2: sparse retrain under the mask
    train_phase(net, xtr, ytr, args.epochs, args.lr / 2, rng, masks=masks,
                tag='sparse')
    acc_sparse = accuracy(net, xte, yte)
    # the mask must really be enforced
    for p, m in masks.items():
        w = p.data().asnumpy()
        assert np.abs(w[m.asnumpy() == 0]).max() == 0.0

    # phase 3: dense retrain (mask lifted)
    train_phase(net, xtr, ytr, args.epochs, args.lr / 4, rng, tag='redense')
    acc_final = accuracy(net, xte, yte)

    logging.info('acc dense %.3f -> pruned %.3f -> sparse %.3f -> final %.3f',
                 acc_dense, acc_pruned, acc_sparse, acc_final)
    assert acc_final >= args.min_acc, acc_final
    assert acc_final >= acc_dense - 0.02, (acc_dense, acc_final)
    print('dsd: dense=%.3f sparse=%.3f final=%.3f'
          % (acc_dense, acc_sparse, acc_final))


if __name__ == '__main__':
    main()
