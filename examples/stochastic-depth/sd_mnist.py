"""Stochastic depth — reference example/stochastic-depth/sd_mnist.py +
sd_module.py (Huang et al. 2016): residual blocks are randomly dropped
during training (identity passthrough) and always kept, scaled by their
survival probability, at inference.

    python sd_mnist.py --epochs 10
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

NCLASS = 5


class SDBlock(gluon.Block):
    """Residual conv block dropped with prob (1 - p_survive) in train
    mode (reference sd_module.py's random-number-gated module list)."""

    def __init__(self, channels, p_survive, **kw):
        super().__init__(**kw)
        self.p_survive = p_survive
        with self.name_scope():
            self.c1 = nn.Conv2D(channels, 3, padding=1, activation='relu')
            self.c2 = nn.Conv2D(channels, 3, padding=1)

    def forward(self, x):
        res = self.c2(self.c1(x))
        if autograd.is_training():
            if float(np.random.rand()) < self.p_survive:
                return mx.nd.relu(x + res)
            return x                           # dropped: identity
        return mx.nd.relu(x + self.p_survive * res)


class SDNet(gluon.Block):
    def __init__(self, n_blocks=4, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.stem = nn.Conv2D(16, 3, padding=1, activation='relu')
            self.blocks = nn.Sequential()
            # linearly decaying survival probability (paper's rule)
            for i in range(n_blocks):
                p = 1.0 - 0.5 * (i + 1) / n_blocks
                self.blocks.add(SDBlock(16, p))
            self.pool = nn.MaxPool2D(2)
            self.out = nn.Dense(NCLASS)

    def forward(self, x):
        return self.out(self.pool(self.blocks(self.stem(x))))


def shapes_data(rng, n, protos):
    """5-class synthetic images from shared prototype patterns."""
    lab = rng.randint(0, NCLASS, n)
    x = protos[lab] + 0.4 * rng.randn(n, 1, 12, 12).astype(np.float32)
    return x.astype(np.float32), lab.astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=10)
    ap.add_argument('--samples', type=int, default=512)
    ap.add_argument('--batch-size', type=int, default=64)
    ap.add_argument('--lr', type=float, default=0.05)
    ap.add_argument('--min-acc', type=float, default=0.9)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(2)
    np.random.seed(2)

    rng = np.random.RandomState(13)
    protos = rng.randn(NCLASS, 1, 12, 12).astype(np.float32)
    xtr, ytr = shapes_data(rng, args.samples, protos)
    xte, yte = shapes_data(rng, args.samples // 4, protos)

    net = SDNet()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': args.lr, 'momentum': 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        perm = rng.permutation(len(xtr))
        tot = 0.0
        for i in range(0, len(xtr), args.batch_size):
            idx = perm[i:i + args.batch_size]
            data, lab = mx.nd.array(xtr[idx]), mx.nd.array(ytr[idx])
            with autograd.record():
                loss = loss_fn(net(data), lab)
            loss.backward()
            # dropped blocks contribute no grads this step — that is the
            # point of stochastic depth
            trainer.step(len(idx), ignore_stale_grad=True)
            tot += float(loss.mean().asscalar()) * len(idx)
        logging.info('epoch %d loss %.4f', epoch, tot / len(xtr))

    pred = net(mx.nd.array(xte)).asnumpy().argmax(axis=1)
    acc = float((pred == yte).mean())
    logging.info('test accuracy %.3f', acc)
    assert acc >= args.min_acc, 'stochastic depth failed: %.3f' % acc
    print('sd_mnist: acc=%.3f' % acc)


if __name__ == '__main__':
    main()
