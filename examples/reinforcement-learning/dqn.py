"""Deep Q-Network (role of reference example/reinforcement-learning/dqn).

The reference's DQN targets Atari through the ALE emulator; this one is
hermetic — a built-in numpy CartPole (the classic pole-balancing
dynamics) — so it runs anywhere the framework does, while exercising
the same machinery the reference example exists to demonstrate: an
online gluon Q-network trained by autograd through a framework
optimizer, a frozen target network synced every N steps, an experience
replay buffer, epsilon-greedy exploration, and the
r + gamma * max_a' Q_target(s', a') bootstrap target.

  python dqn.py --episodes 150
"""
import argparse
import collections
import logging
import random

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


class CartPole:
    """Classic cart-pole balancing dynamics (Barto, Sutton & Anderson
    1983 formulation): state (x, x', theta, theta'), actions {push
    left, push right}, reward 1 per step until |theta|>12deg or
    |x|>2.4, capped at `horizon`."""

    GRAV, MCART, MPOLE, LEN, FORCE, TAU = 9.8, 1.0, 0.1, 0.5, 10.0, 0.02
    THETA_LIM, X_LIM = 12 * np.pi / 180, 2.4

    def __init__(self, seed, horizon=200):
        self.rng = np.random.RandomState(seed)
        self.horizon = horizon

    def reset(self):
        self.s = self.rng.uniform(-0.05, 0.05, 4).astype(np.float32)
        self.t = 0
        return self.s.copy()

    def step(self, action):
        x, xd, th, thd = self.s
        force = self.FORCE if action == 1 else -self.FORCE
        mtot = self.MCART + self.MPOLE
        pml = self.MPOLE * self.LEN
        tmp = (force + pml * thd * thd * np.sin(th)) / mtot
        thacc = (self.GRAV * np.sin(th) - np.cos(th) * tmp) / \
            (self.LEN * (4.0 / 3.0 - self.MPOLE * np.cos(th) ** 2 / mtot))
        xacc = tmp - pml * thacc * np.cos(th) / mtot
        self.s = np.array([x + self.TAU * xd, xd + self.TAU * xacc,
                           th + self.TAU * thd, thd + self.TAU * thacc],
                          np.float32)
        self.t += 1
        done = (abs(self.s[0]) > self.X_LIM
                or abs(self.s[2]) > self.THETA_LIM
                or self.t >= self.horizon)
        return self.s.copy(), 1.0, done


def q_net(hidden):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(hidden, activation='relu'),
            gluon.nn.Dense(hidden, activation='relu'),
            gluon.nn.Dense(2))
    return net


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--episodes', type=int, default=300)
    ap.add_argument('--hidden', type=int, default=64)
    ap.add_argument('--batch-size', type=int, default=64)
    ap.add_argument('--replay', type=int, default=10000)
    ap.add_argument('--gamma', type=float, default=0.99)
    ap.add_argument('--lr', type=float, default=1e-3)
    ap.add_argument('--target-sync', type=int, default=200)
    ap.add_argument('--train-freq', type=int, default=1,
                    help='gradient step every N env steps (1 = the '
                         'classic per-step schedule)')
    ap.add_argument('--eps-decay', type=float, default=0.995)
    ap.add_argument('--min-return', type=float, default=0.0,
                    help='assert the trailing-20-episode mean return '
                         'exceeds this (smoke-test gate)')
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)
    np.random.seed(0)
    random.seed(0)
    ctx = mx.cpu()

    online, target = q_net(args.hidden), q_net(args.hidden)
    online.initialize(mx.init.Xavier(), ctx=ctx)
    target.initialize(mx.init.Xavier(), ctx=ctx)
    online.hybridize()
    target.hybridize()
    # resolve deferred shapes before the first target sync
    warm = mx.nd.zeros((1, 4), ctx=ctx)
    online(warm)
    target(warm)

    def sync_target():
        for (_, po), (_, pt) in zip(online.collect_params().items(),
                                    target.collect_params().items()):
            pt.set_data(po.data())

    sync_target()
    trainer = gluon.Trainer(online.collect_params(), 'adam',
                            {'learning_rate': args.lr})
    loss_fn = gluon.loss.L2Loss()
    buf = collections.deque(maxlen=args.replay)
    env = CartPole(seed=1)
    eps, step, returns = 1.0, 0, []

    for ep in range(args.episodes):
        s = env.reset()
        ret, done = 0.0, False
        while not done:
            if random.random() < eps:
                a = random.randrange(2)
            else:
                q = online(mx.nd.array(s[None], ctx=ctx)).asnumpy()
                a = int(q.argmax())
            s2, r, done = env.step(a)
            # terminal-by-horizon is not a true terminal for bootstrap
            truncated = done and env.t >= env.horizon
            buf.append((s, a, r, s2, 0.0 if truncated else float(done)))
            s = s2
            ret += r
            step += 1
            if len(buf) >= args.batch_size and step % args.train_freq == 0:
                batch = random.sample(buf, args.batch_size)
                bs, ba, br, bs2, bd = map(np.array, zip(*batch))
                S = mx.nd.array(bs, ctx=ctx)
                S2 = mx.nd.array(bs2, ctx=ctx)
                qn = target(S2).max(axis=1).asnumpy()
                y = br + args.gamma * qn * (1.0 - bd)
                Y = mx.nd.array(y.astype(np.float32), ctx=ctx)
                A = mx.nd.array(ba.astype(np.float32), ctx=ctx)
                with autograd.record():
                    q = online(S)
                    q_a = (q * mx.nd.one_hot(A, 2)).sum(axis=1)
                    loss = loss_fn(q_a, Y)
                loss.backward()
                trainer.step(args.batch_size)
            if step % args.target_sync == 0:
                sync_target()
        returns.append(ret)
        eps = max(0.05, eps * args.eps_decay)
        if (ep + 1) % 20 == 0:
            logging.info('episode %d return(mean20)=%.1f eps=%.2f',
                         ep + 1, np.mean(returns[-20:]), eps)

    mean20 = float(np.mean(returns[-20:]))
    early = float(np.mean(returns[:20]))
    logging.info('dqn done: first20=%.1f last20=%.1f', early, mean20)
    assert np.isfinite(mean20)
    assert mean20 > args.min_return, (mean20, args.min_return)


if __name__ == '__main__':
    main()
