"""Profiler demo — reference example/profiler/profiler_executor.py:
wrap a training loop in profiler start/stop and dump a Chrome
trace-event JSON (load it at chrome://tracing or Perfetto). The
TPU-native profiler also mirrors into a jax/XLA trace directory for
TensorBoard when the backend supports it.

    python profiler_demo.py --steps 20
"""
import argparse
import json
import logging
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--steps', type=int, default=20)
    ap.add_argument('--batch-size', type=int, default=32)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    out = os.path.join(tempfile.mkdtemp(), 'profile.json')
    rng = np.random.RandomState(1)
    x = rng.randn(args.batch_size, 64).astype(np.float32)
    y = rng.randint(0, 4, args.batch_size).astype(np.float32)

    data = mx.sym.Variable('data')
    net = mx.sym.FullyConnected(data, num_hidden=32, name='fc1')
    net = mx.sym.Activation(net, act_type='relu')
    net = mx.sym.FullyConnected(net, num_hidden=4, name='fc2')
    net = mx.sym.SoftmaxOutput(net, name='softmax')
    exe = net.simple_bind(mx.current_context(),
                          data=(args.batch_size, 64),
                          softmax_label=(args.batch_size,))
    exe.arg_dict['data'][:] = x
    exe.arg_dict['softmax_label'][:] = y

    mx.profiler.profiler_set_config(mode='all', filename=out)
    mx.profiler.profiler_set_state('run')
    for _ in range(args.steps):
        exe.forward(is_train=True)
        # no head grads: SoftmaxOutput is a loss layer, and arg-less
        # backward keeps the executor's fused fwd+bwd path (passing
        # exe.outputs would materialize a second, separate forward)
        exe.backward()
        for k, g in exe.grad_dict.items():
            if g is not None and k not in ('data', 'softmax_label'):
                exe.arg_dict[k][:] = exe.arg_dict[k] - 0.05 * g
    mx.nd.waitall()
    mx.profiler.profiler_set_state('stop')
    mx.profiler.dump_profile()

    with open(out) as f:
        trace = json.load(f)
    events = trace['traceEvents']
    logging.info('captured %d trace events -> %s', len(events), out)
    assert events, 'profiler captured nothing'
    assert any(e.get('ph') == 'X' for e in events)
    print('profiler_demo: %d events' % len(events))


if __name__ == '__main__':
    main()
