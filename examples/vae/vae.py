"""Variational autoencoder — reference example/vae/VAE.py: Gaussian
encoder q(z|x), Bernoulli-style decoder p(x|z), ELBO = reconstruction +
KL(q || N(0,I)) with the reparameterization trick. Hermetic: synthetic
two-cluster images so the latent space is exactly 2-separable.

    python vae.py --epochs 15
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

DIM = 144  # 12x12
NZ = 4


class VAE(gluon.Block):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.enc = nn.Dense(32, activation='tanh')
            self.mu = nn.Dense(NZ)
            self.logvar = nn.Dense(NZ)
            self.dec1 = nn.Dense(32, activation='tanh')
            self.dec2 = nn.Dense(DIM)

    def forward(self, x):
        h = self.enc(x)
        mu, logvar = self.mu(h), self.logvar(h)
        eps = mx.nd.random.normal(shape=mu.shape)
        z = mu + eps * (0.5 * logvar).exp()
        y = self.dec2(self.dec1(z))
        return y, mu, logvar


def elbo_loss(y, x, mu, logvar):
    # Bernoulli recon via logits + analytic KL (reference VAE.py loss)
    recon = mx.nd.log(1 + mx.nd.exp(y)) - x * y            # softplus CE
    recon = recon.sum(axis=1)
    kl = -0.5 * (1 + logvar - mu * mu - logvar.exp()).sum(axis=1)
    return (recon + kl).mean()


def clusters(rng, n):
    protos = (rng.rand(2, DIM) > 0.5).astype(np.float32)
    lab = rng.randint(0, 2, n)
    x = protos[lab].copy()
    flip = rng.rand(n, DIM) < 0.05
    x[flip] = 1 - x[flip]
    return x.astype(np.float32), lab


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=15)
    ap.add_argument('--samples', type=int, default=512)
    ap.add_argument('--batch-size', type=int, default=64)
    ap.add_argument('--lr', type=float, default=2e-3)
    ap.add_argument('--min-gain', type=float, default=30.0,
                    help='required ELBO improvement (nats) over epoch 0')
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)

    rng = np.random.RandomState(9)
    x, _ = clusters(rng, args.samples)

    net = VAE()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})

    first = last = None
    for epoch in range(args.epochs):
        perm = rng.permutation(len(x))
        tot = 0.0
        for i in range(0, len(x), args.batch_size):
            data = mx.nd.array(x[perm[i:i + args.batch_size]])
            with autograd.record():
                y, mu, logvar = net(data)
                loss = elbo_loss(y, data, mu, logvar)
            loss.backward()
            trainer.step(data.shape[0])
            tot += float(loss.asscalar()) * data.shape[0]
        tot /= len(x)
        if first is None:
            first = tot
        last = tot
        logging.info('epoch %d -ELBO %.2f', epoch, tot)

    gain = first - last
    assert gain >= args.min_gain, \
        'ELBO barely improved: %.2f -> %.2f' % (first, last)
    print('vae: neg_elbo %.2f -> %.2f (gain %.2f nats)' %
          (first, last, gain))


if __name__ == '__main__':
    main()
