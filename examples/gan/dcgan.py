"""DCGAN via the two-Module GAN dance (role of reference
example/gan/dcgan.py).

Covers the Module APIs a GAN needs and nothing else exercises
together: two independently-bound Modules, discriminator gradients
ACCUMULATED across the real and fake half-batches (grad_req='add' —
the reference trains D exactly this way), and the generator updated
from the discriminator's INPUT gradients (get_input_grads →
modG.backward(out_grads)).

Runs hermetically: the "dataset" is synthetic two-moons-style blob
images (no sklearn/cv2/matplotlib deps); success is measured by the
adversarial losses staying finite and the generator's output
statistics moving toward the data statistics.

  python dcgan.py --epochs 2 --batch-size 16 --image-size 16
"""
import argparse
import logging

import numpy as np

import mxnet_tpu as mx


def make_generator(ngf, nc, image_size, no_bias=True, fix_gamma=True,
                   eps=1e-5 + 1e-12):
    """Noise (B, code, 1, 1) → image (B, nc, S, S) via stride-2
    Deconvolutions, each followed by BatchNorm + ReLU, tanh head."""
    assert image_size in (16, 32, 64)
    n_up = {16: 2, 32: 3, 64: 4}[image_size]
    x = mx.sym.Variable('rand')
    # 1x1 → 4x4
    x = mx.sym.Deconvolution(x, kernel=(4, 4), num_filter=ngf * (2 ** n_up),
                             no_bias=no_bias, name='gen_head')
    x = mx.sym.BatchNorm(x, fix_gamma=fix_gamma, eps=eps, name='gen_head_bn')
    x = mx.sym.Activation(x, act_type='relu')
    for i in range(n_up - 1):
        x = mx.sym.Deconvolution(
            x, kernel=(4, 4), stride=(2, 2), pad=(1, 1),
            num_filter=ngf * (2 ** (n_up - 1 - i)), no_bias=no_bias,
            name='gen_up%d' % i)
        x = mx.sym.BatchNorm(x, fix_gamma=fix_gamma, eps=eps,
                             name='gen_up%d_bn' % i)
        x = mx.sym.Activation(x, act_type='relu')
    x = mx.sym.Deconvolution(x, kernel=(4, 4), stride=(2, 2), pad=(1, 1),
                             num_filter=nc, no_bias=no_bias, name='gen_out')
    return mx.sym.Activation(x, act_type='tanh', name='gen_tanh')


def make_discriminator(ndf, image_size, no_bias=True, fix_gamma=True,
                       eps=1e-5 + 1e-12):
    """Image → logistic real/fake probability (stride-2 convs +
    LeakyReLU, BN on all but the first, LogisticRegressionOutput head
    so the label feeds the loss like the reference's)."""
    n_down = {16: 2, 32: 3, 64: 4}[image_size]
    label = mx.sym.Variable('label')
    x = mx.sym.Variable('data')
    for i in range(n_down):
        x = mx.sym.Convolution(x, kernel=(4, 4), stride=(2, 2), pad=(1, 1),
                               num_filter=ndf * (2 ** i), no_bias=no_bias,
                               name='disc_dn%d' % i)
        if i > 0:
            x = mx.sym.BatchNorm(x, fix_gamma=fix_gamma, eps=eps,
                                 name='disc_dn%d_bn' % i)
        x = mx.sym.LeakyReLU(x, act_type='leaky', slope=0.2)
    x = mx.sym.Convolution(x, kernel=(4, 4), num_filter=1, no_bias=no_bias,
                           name='disc_out')
    x = mx.sym.Flatten(x)
    return mx.sym.LogisticRegressionOutput(data=x, label=label,
                                           name='dloss')


def blob_batches(n, batch, size, nc, seed):
    """Synthetic dataset: soft gaussian blobs at grid positions, in
    [-1, 1] like a tanh generator's range."""
    rng = np.random.RandomState(seed)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
    imgs = []
    for _ in range(n):
        cy, cx = rng.uniform(size * 0.25, size * 0.75, 2)
        r = rng.uniform(size * 0.1, size * 0.2)
        img = np.exp(-((yy - cy) ** 2 + (xx - cx) ** 2) / (2 * r * r))
        imgs.append(np.repeat(img[None], nc, 0))
    data = np.stack(imgs) * 2 - 1
    for s in range(0, n - batch + 1, batch):
        yield data[s:s + batch]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=3)
    ap.add_argument('--batch-size', type=int, default=16)
    ap.add_argument('--samples', type=int, default=256)
    ap.add_argument('--image-size', type=int, default=16)
    ap.add_argument('--code', type=int, default=32)
    ap.add_argument('--ngf', type=int, default=16)
    ap.add_argument('--ndf', type=int, default=16)
    ap.add_argument('--nc', type=int, default=1)
    ap.add_argument('--lr', type=float, default=2e-4)
    args = ap.parse_args()
    assert args.samples >= args.batch_size, \
        '--samples must cover at least one batch'
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(42)
    np.random.seed(42)
    B, S = args.batch_size, args.image_size
    ctx = mx.cpu()

    symG = make_generator(args.ngf, args.nc, S)
    symD = make_discriminator(args.ndf, S)

    modG = mx.mod.Module(symG, data_names=('rand',), label_names=None,
                         context=ctx)
    modG.bind(data_shapes=[('rand', (B, args.code, 1, 1))])
    modG.init_params(initializer=mx.init.Normal(0.02))
    modG.init_optimizer(optimizer='adam',
                        optimizer_params={'learning_rate': args.lr,
                                          'beta1': 0.5})

    modD = mx.mod.Module(symD, data_names=('data',), label_names=('label',),
                         context=ctx)
    # inputs_need_grad: the generator trains on D's input gradients;
    # grad_req='add' accumulates the real and fake half-batch grads
    # before one update, exactly the reference recipe
    modD.bind(data_shapes=[('data', (B, args.nc, S, S))],
              label_shapes=[('label', (B,))],
              inputs_need_grad=True, grad_req='add')
    modD.init_params(initializer=mx.init.Normal(0.02))
    modD.init_optimizer(optimizer='adam',
                        optimizer_params={'learning_rate': args.lr,
                                          'beta1': 0.5})

    ones = mx.nd.ones((B,), ctx=ctx)
    zeros = mx.nd.zeros((B,), ctx=ctx)

    def zero_d_grads():
        for e in modD._exec_group.execs:
            for g in e.grad_arrays:
                if g is not None:
                    g[:] = 0.0

    d_losses, g_losses, g_means = [], [], []
    for epoch in range(args.epochs):
        for real in blob_batches(args.samples, B, S, args.nc, seed=epoch):
            noise = mx.nd.array(
                np.random.randn(B, args.code, 1, 1).astype(np.float32))
            modG.forward(mx.io.DataBatch([noise], []), is_train=True)
            fake = modG.get_outputs()[0]

            # -- D: accumulate real(label 1) + fake(label 0) grads ----
            zero_d_grads()
            modD.forward(mx.io.DataBatch([mx.nd.array(real)], [ones]),
                         is_train=True)
            p_real = modD.get_outputs()[0].asnumpy()
            modD.backward()
            modD.forward(mx.io.DataBatch([fake.copy()], [zeros]),
                         is_train=True)
            p_fake = modD.get_outputs()[0].asnumpy()
            modD.backward()
            modD.update()
            eps = 1e-7
            d_losses.append(float(
                -np.log(p_real + eps).mean() - np.log(1 - p_fake + eps).mean()))

            # -- G: ascend D's input gradient at label=1 --------------
            zero_d_grads()
            modD.forward(mx.io.DataBatch([fake], [ones]), is_train=True)
            p_gen = modD.get_outputs()[0].asnumpy()
            modD.backward()
            grads_to_g = modD.get_input_grads()
            modG.backward(grads_to_g)
            modG.update()
            g_losses.append(float(-np.log(p_gen + eps).mean()))
            g_means.append(float(fake.asnumpy().mean()))
        logging.info('epoch %d dloss=%.3f gloss=%.3f gen_mean=%.3f',
                     epoch, np.mean(d_losses[-8:]), np.mean(g_losses[-8:]),
                     g_means[-1])

    assert np.isfinite(d_losses).all() and np.isfinite(g_losses).all()
    # the generator must have moved: its outputs start near tanh(BN(0))
    # ~ 0-mean noise and drift toward the blob data's statistics
    assert abs(g_means[-1] - g_means[0]) > 1e-3 or len(g_means) < 4
    logging.info('dcgan ok: %d G steps', len(g_losses))


if __name__ == '__main__':
    main()
