"""Memory-cost / rematerialization demo — reference example/memcost/
(inception_memcost.py + the mirror notes): trade compute for activation
memory with backward mirroring. Here the switch is
MXTPU_BACKWARD_DO_MIRROR=1 (`jax.checkpoint` policies in the fused
executor, executor.py) — this script trains the same deep MLP with and
without mirroring in two subprocesses and asserts identical
convergence, printing the traced-HLO peak-memory estimates.

    python memcost.py --epochs 4
"""
import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

WORKER = r'''
import json, sys
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
sys.path.insert(0, %(root)r)
import mxnet_tpu as mx

mx.random.seed(3)
rng = np.random.RandomState(0)
x = rng.randn(256, 64).astype('float32')
y = (x[:, :8].sum(axis=1) > 0).astype('float32')

data = mx.sym.Variable('data')
net = data
for i in range(%(depth)d):
    net = mx.sym.Activation(mx.sym.FullyConnected(
        net, num_hidden=64, name='fc%%d' %% i), act_type='relu')
net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(net, num_hidden=2,
                                                 name='out'), name='softmax')

it = mx.io.NDArrayIter(x, y, 32, label_name='softmax_label')
mod = mx.mod.Module(net, label_names=('softmax_label',))
mod.fit(it, num_epoch=%(epochs)d, optimizer='sgd',
        initializer=mx.init.Xavier(),
        optimizer_params={'learning_rate': 0.05, 'momentum': 0.9})
acc = dict(mod.score(it, 'acc'))['accuracy']
print(json.dumps({'acc': float(acc),
                  'mirror': bool(int(__import__('os').environ.get(
                      'MXTPU_BACKWARD_DO_MIRROR', '0')))}))
'''


def run(mirror, args):
    env = dict(os.environ)
    env['MXTPU_BACKWARD_DO_MIRROR'] = '1' if mirror else '0'
    code = WORKER % {'root': os.path.join(os.path.dirname(
        os.path.abspath(__file__)), '..', '..'),
        'depth': args.depth, 'epochs': args.epochs}
    out = subprocess.run([sys.executable, '-c', code], env=env,
                         capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=15)
    ap.add_argument('--depth', type=int, default=8)
    args = ap.parse_args()

    plain = run(False, args)
    mirrored = run(True, args)
    print('plain   :', plain)
    print('mirrored:', mirrored)
    # rematerialization must not change the math
    assert abs(plain['acc'] - mirrored['acc']) < 1e-3, (plain, mirrored)
    assert plain['acc'] > 0.9, plain
    print('memcost: acc=%.3f identical with and without remat'
          % plain['acc'])


if __name__ == '__main__':
    main()
