"""L2-SVM output layer — reference example/svm_mnist/svm_mnist.py.

MLP trained with the SVMOutput symbol (squared hinge loss on the margin)
instead of softmax, via the Module API. Hermetic: separable Gaussian
blobs stand in for the PCA-projected MNIST of the reference; both the
L2-SVM (default) and L1-SVM (--use-linear) objectives are exercised.

    python svm_mnist.py --epochs 10
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx

NCLASS = 10
DIM = 48


def blobs(rng, n, centers):
    labels = rng.randint(0, NCLASS, size=n)
    x = centers[labels] + 0.4 * rng.randn(n, DIM).astype(np.float32)
    return x.astype(np.float32), labels.astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=10)
    ap.add_argument('--samples', type=int, default=640)
    ap.add_argument('--batch-size', type=int, default=64)
    ap.add_argument('--lr', type=float, default=0.005,
                    help='the hinge gradient is unnormalized (reference '
                         'svm_output-inl.h), so keep lr small')
    ap.add_argument('--use-linear', action='store_true',
                    help='L1-SVM objective instead of L2-SVM')
    ap.add_argument('--min-acc', type=float, default=0.9)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(3)
    centers = rng.randn(NCLASS, DIM).astype(np.float32) * 1.8
    xtr, ytr = blobs(rng, args.samples, centers)
    xte, yte = blobs(rng, args.samples // 2, centers)
    train = mx.io.NDArrayIter(xtr, ytr, args.batch_size, shuffle=True,
                              label_name='svm_label')
    val = mx.io.NDArrayIter(xte, yte, args.batch_size,
                            label_name='svm_label')

    data = mx.sym.Variable('data')
    fc1 = mx.sym.FullyConnected(data=data, num_hidden=128, name='fc1')
    act1 = mx.sym.Activation(data=fc1, act_type='relu', name='relu1')
    fc2 = mx.sym.FullyConnected(data=act1, num_hidden=64, name='fc2')
    act2 = mx.sym.Activation(data=fc2, act_type='relu', name='relu2')
    fc3 = mx.sym.FullyConnected(data=act2, num_hidden=NCLASS, name='fc3')
    net = mx.sym.SVMOutput(data=fc3, name='svm',
                           use_linear=args.use_linear)

    mod = mx.mod.Module(symbol=net, context=mx.current_context(),
                        label_names=('svm_label',))
    mod.fit(train, eval_data=val, eval_metric='acc', optimizer='sgd',
            optimizer_params={'learning_rate': args.lr, 'momentum': 0.9,
                              'wd': 1e-4},
            num_epoch=args.epochs)
    score = dict(mod.score(val, ['acc']))
    logging.info('validation acc %.3f', score['accuracy'])
    assert score['accuracy'] >= args.min_acc, score
    print('svm_mnist: acc=%.3f' % score['accuracy'])


if __name__ == '__main__':
    main()
