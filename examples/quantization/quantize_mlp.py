"""Post-training quantization — reference contrib quantize/dequantize
ops (src/operator/contrib/quantize.cc): train an MLP in float, quantize
its weights to uint8 with per-tensor min/max calibration, run inference
with on-the-fly dequantize, and gate the accuracy drop.

    python quantize_mlp.py --epochs 8
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

NCLASS = 8
DIM = 48


def blobs(rng, n, centers):
    lab = rng.randint(0, NCLASS, n)
    x = centers[lab] + 0.45 * rng.randn(n, DIM).astype(np.float32)
    return x.astype(np.float32), lab.astype(np.float32)


def quantize_params(net):
    """uint8-quantize every weight/bias; returns {name: (q, mn, mx)}."""
    stored = {}
    for name, p in net.collect_params().items():
        w = p.data()
        w_np = w.asnumpy()
        lo = float(w_np.min())
        hi = float(w_np.max()) + 1e-8
        q, qmin, qmax = mx.nd.contrib.quantize(
            w, mx.nd.array([lo]), mx.nd.array([hi]), out_type='uint8')
        stored[name] = (q, qmin, qmax)
    return stored


def load_quantized(net, stored):
    for name, p in net.collect_params().items():
        q, qmin, qmax = stored[name]
        deq = mx.nd.contrib.dequantize(q, qmin, qmax, out_type='float32')
        p.set_data(deq.reshape(p.data().shape))


def accuracy(net, x, y):
    return float((net(mx.nd.array(x)).asnumpy().argmax(1) == y).mean())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=8)
    ap.add_argument('--samples', type=int, default=768)
    ap.add_argument('--lr', type=float, default=0.1)
    ap.add_argument('--max-drop', type=float, default=0.02,
                    help='allowed accuracy drop after uint8 quantization')
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(10)

    rng = np.random.RandomState(23)
    centers = rng.randn(NCLASS, DIM).astype(np.float32) * 1.6
    xtr, ytr = blobs(rng, args.samples, centers)
    xte, yte = blobs(rng, args.samples // 4, centers)

    net = nn.Sequential()
    with net.name_scope():
        net.add(nn.Dense(64, activation='relu'), nn.Dense(NCLASS))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': args.lr, 'momentum': 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for epoch in range(args.epochs):
        perm = rng.permutation(len(xtr))
        for i in range(0, len(xtr), 64):
            idx = perm[i:i + 64]
            with autograd.record():
                loss = loss_fn(net(mx.nd.array(xtr[idx])),
                               mx.nd.array(ytr[idx]))
            loss.backward()
            trainer.step(len(idx))

    acc_fp32 = accuracy(net, xte, yte)
    stored = quantize_params(net)
    nbytes_fp32 = sum(p.data().size * 4
                      for p in net.collect_params().values())
    nbytes_q = sum(q.size + 8 for q, _, _ in stored.values())
    load_quantized(net, stored)
    acc_q = accuracy(net, xte, yte)

    logging.info('fp32 acc %.3f -> uint8 acc %.3f (weights %.1fx smaller)',
                 acc_fp32, acc_q, nbytes_fp32 / nbytes_q)
    assert acc_fp32 > 0.9, acc_fp32
    assert acc_fp32 - acc_q <= args.max_drop, (acc_fp32, acc_q)
    print('quantize_mlp: fp32=%.3f uint8=%.3f compression=%.1fx'
          % (acc_fp32, acc_q, nbytes_fp32 / nbytes_q))


if __name__ == '__main__':
    main()
