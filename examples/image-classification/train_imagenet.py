"""Train ImageNet-class networks (ResNet) with Module + KVStore —
BASELINE config #2 and the bench.py headline workload.

Mirrors example/image-classification/train_imagenet.py: symbolic ResNet,
RecordIO/synthetic data, data-parallel fit over all local devices via
KVStore('device') semantics (on TPU: psum over the mesh inside one
compiled step).

    python train_imagenet.py --network resnet --num-layers 50 \
        --benchmark 1 --batch-size 32
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx
from symbols.resnet import get_symbol


def synthetic_imagenet_iter(batch_size, image_shape, num_classes, samples):
    rng = np.random.RandomState(0)
    data = rng.standard_normal((samples,) + image_shape).astype('float32')
    label = rng.randint(0, num_classes, samples).astype('float32')
    return mx.io.NDArrayIter(data, label, batch_size=batch_size,
                             shuffle=True, label_name='softmax_label')


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--network', default='resnet')
    parser.add_argument('--num-layers', type=int, default=50)
    parser.add_argument('--batch-size', type=int, default=32)
    parser.add_argument('--image-shape', default='3,224,224')
    parser.add_argument('--num-classes', type=int, default=1000)
    parser.add_argument('--num-epochs', type=int, default=1)
    parser.add_argument('--lr', type=float, default=0.1)
    parser.add_argument('--kv-store', default='device')
    parser.add_argument('--benchmark', type=int, default=0,
                        help='use synthetic data (no dataset needed)')
    parser.add_argument('--samples', type=int, default=256)
    parser.add_argument('--data-train', default=None,
                        help='RecordIO file of packed images')
    parser.add_argument('--model-prefix', default=None)
    parser.add_argument('--dtype', default='float32',
                        choices=['float32', 'float16'],
                        help='float16 casts after data so every weight '
                             'trains in half precision (bf16 on TPU '
                             'under MXTPU_F16_AS_BF16)')
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    image_shape = tuple(int(x) for x in args.image_shape.split(','))
    if args.benchmark or not args.data_train:
        train = synthetic_imagenet_iter(args.batch_size, image_shape,
                                        args.num_classes, args.samples)
    else:
        train = mx.io.ImageRecordIter(
            path_imgrec=args.data_train, data_shape=image_shape,
            batch_size=args.batch_size, shuffle=True)

    sym = get_symbol(num_classes=args.num_classes,
                     num_layers=args.num_layers,
                     image_shape=args.image_shape, dtype=args.dtype)
    mod = mx.mod.Module(symbol=sym, context=mx.current_context())
    mod.fit(train,
            eval_metric=['acc'],
            kvstore=args.kv_store,
            optimizer='sgd',
            optimizer_params={'learning_rate': args.lr, 'momentum': 0.9,
                              'wd': 1e-4,
                              'multi_precision': args.dtype == 'float16'},
            initializer=mx.init.Xavier(rnd_type='gaussian',
                                       factor_type='in', magnitude=2),
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 10),
            epoch_end_callback=(mx.callback.do_checkpoint(args.model_prefix)
                                if args.model_prefix else None),
            num_epoch=args.num_epochs)
    return mod


if __name__ == '__main__':
    main()
