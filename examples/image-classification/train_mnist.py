"""Train LeNet / MLP on MNIST with the Module API — BASELINE config #1.

Mirrors example/image-classification/train_mnist.py in the reference:
symbolic network definition, MNISTIter, Module.fit with kvstore,
Speedometer + checkpoint callbacks. Runs hermetically (synthetic MNIST)
when the idx files are absent.

    python train_mnist.py --network lenet --num-epochs 5
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx


def mlp():
    """Reference example/image-classification/symbols/mlp.py."""
    data = mx.sym.Variable('data')
    data = mx.sym.Flatten(data=data)
    fc1 = mx.sym.FullyConnected(data=data, num_hidden=128, name='fc1')
    act1 = mx.sym.Activation(data=fc1, act_type='relu', name='relu1')
    fc2 = mx.sym.FullyConnected(data=act1, num_hidden=64, name='fc2')
    act2 = mx.sym.Activation(data=fc2, act_type='relu', name='relu2')
    fc3 = mx.sym.FullyConnected(data=act2, num_hidden=10, name='fc3')
    return mx.sym.SoftmaxOutput(data=fc3, name='softmax')


def lenet():
    """Reference example/image-classification/symbols/lenet.py."""
    data = mx.sym.Variable('data')
    conv1 = mx.sym.Convolution(data=data, kernel=(5, 5), num_filter=20)
    act1 = mx.sym.Activation(data=conv1, act_type='tanh')
    pool1 = mx.sym.Pooling(data=act1, pool_type='max', kernel=(2, 2),
                           stride=(2, 2))
    conv2 = mx.sym.Convolution(data=pool1, kernel=(5, 5), num_filter=50)
    act2 = mx.sym.Activation(data=conv2, act_type='tanh')
    pool2 = mx.sym.Pooling(data=act2, pool_type='max', kernel=(2, 2),
                           stride=(2, 2))
    flat = mx.sym.Flatten(data=pool2)
    fc1 = mx.sym.FullyConnected(data=flat, num_hidden=500)
    act3 = mx.sym.Activation(data=fc1, act_type='tanh')
    fc2 = mx.sym.FullyConnected(data=act3, num_hidden=10)
    return mx.sym.SoftmaxOutput(data=fc2, name='softmax')


def main():
    parser = argparse.ArgumentParser(description='train mnist')
    parser.add_argument('--network', default='mlp',
                        choices=('mlp', 'lenet'))
    parser.add_argument('--batch-size', type=int, default=64)
    parser.add_argument('--num-epochs', type=int, default=3)
    parser.add_argument('--lr', type=float, default=0.1)
    parser.add_argument('--kv-store', default='local')
    parser.add_argument('--data-dir', default='data')
    parser.add_argument('--model-prefix', default=None)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    flat = args.network == 'mlp'
    train = mx.io.MNISTIter(
        image=os.path.join(args.data_dir, 'train-images-idx3-ubyte'),
        label=os.path.join(args.data_dir, 'train-labels-idx1-ubyte'),
        batch_size=args.batch_size, flat=flat, shuffle=True)
    val = mx.io.MNISTIter(
        image=os.path.join(args.data_dir, 't10k-images-idx3-ubyte'),
        label=os.path.join(args.data_dir, 't10k-labels-idx1-ubyte'),
        batch_size=args.batch_size, flat=flat, shuffle=False)

    net = mlp() if args.network == 'mlp' else lenet()
    mod = mx.mod.Module(symbol=net, context=mx.current_context())
    cb = [mx.callback.Speedometer(args.batch_size, 50)]
    epoch_cb = (mx.callback.do_checkpoint(args.model_prefix)
                if args.model_prefix else None)
    mod.fit(train, eval_data=val, eval_metric='acc',
            optimizer='sgd',
            optimizer_params={'learning_rate': args.lr, 'momentum': 0.9},
            kvstore=args.kv_store,
            initializer=mx.init.Xavier(),
            batch_end_callback=cb, epoch_end_callback=epoch_cb,
            num_epoch=args.num_epochs)
    score = mod.score(val, mx.metric.Accuracy())
    for name, acc in score:
        logging.info('final validation %s = %.4f', name, acc)
    return score


if __name__ == '__main__':
    main()
