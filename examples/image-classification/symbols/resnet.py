"""Symbolic ResNet v1.5/v2 builder.

Mirrors the role of example/image-classification/symbols/resnet.py in
the reference (residual units + stage layout per depth); written against
the mxnet_tpu Symbol API.
"""
import mxnet_tpu as mx

# depth -> (bottleneck?, units per stage)
_CONFIGS = {
    18: (False, [2, 2, 2, 2]),
    34: (False, [3, 4, 6, 3]),
    50: (True, [3, 4, 6, 3]),
    101: (True, [3, 4, 23, 3]),
    152: (True, [3, 8, 36, 3]),
}


def residual_unit(data, num_filter, stride, dim_match, name, bottleneck):
    if bottleneck:
        bn1 = mx.sym.BatchNorm(data=data, fix_gamma=False, name=name + '_bn1')
        act1 = mx.sym.Activation(data=bn1, act_type='relu', name=name + '_relu1')
        conv1 = mx.sym.Convolution(data=act1, num_filter=num_filter // 4,
                                   kernel=(1, 1), stride=(1, 1), pad=(0, 0),
                                   no_bias=True, name=name + '_conv1')
        bn2 = mx.sym.BatchNorm(data=conv1, fix_gamma=False, name=name + '_bn2')
        act2 = mx.sym.Activation(data=bn2, act_type='relu', name=name + '_relu2')
        conv2 = mx.sym.Convolution(data=act2, num_filter=num_filter // 4,
                                   kernel=(3, 3), stride=stride, pad=(1, 1),
                                   no_bias=True, name=name + '_conv2')
        bn3 = mx.sym.BatchNorm(data=conv2, fix_gamma=False, name=name + '_bn3')
        act3 = mx.sym.Activation(data=bn3, act_type='relu', name=name + '_relu3')
        conv3 = mx.sym.Convolution(data=act3, num_filter=num_filter,
                                   kernel=(1, 1), stride=(1, 1), pad=(0, 0),
                                   no_bias=True, name=name + '_conv3')
        body = conv3
        shortcut_from = act1
    else:
        bn1 = mx.sym.BatchNorm(data=data, fix_gamma=False, name=name + '_bn1')
        act1 = mx.sym.Activation(data=bn1, act_type='relu', name=name + '_relu1')
        conv1 = mx.sym.Convolution(data=act1, num_filter=num_filter,
                                   kernel=(3, 3), stride=stride, pad=(1, 1),
                                   no_bias=True, name=name + '_conv1')
        bn2 = mx.sym.BatchNorm(data=conv1, fix_gamma=False, name=name + '_bn2')
        act2 = mx.sym.Activation(data=bn2, act_type='relu', name=name + '_relu2')
        body = mx.sym.Convolution(data=act2, num_filter=num_filter,
                                  kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                                  no_bias=True, name=name + '_conv2')
        shortcut_from = act1
    if dim_match:
        shortcut = data
    else:
        shortcut = mx.sym.Convolution(data=shortcut_from,
                                      num_filter=num_filter, kernel=(1, 1),
                                      stride=stride, no_bias=True,
                                      name=name + '_sc')
    return body + shortcut


def get_symbol(num_classes=1000, num_layers=50, image_shape='3,224,224',
               dtype='float32', **kwargs):
    bottleneck, units = _CONFIGS[num_layers]
    channels = [int(x) for x in image_shape.split(',')][0]  # noqa: F841
    filters = ([64, 256, 512, 1024, 2048] if bottleneck
               else [64, 64, 128, 256, 512])

    data = mx.sym.Variable('data')
    if dtype == 'float16':
        # the reference symbol's fp16 mode: one cast after data, so
        # every weight downstream infers half precision (bf16 under
        # MXTPU_F16_AS_BF16); the loss head computes in fp32 below
        data = mx.sym.Cast(data=data, dtype='float16')
    body = mx.sym.Convolution(data=data, num_filter=filters[0],
                              kernel=(7, 7), stride=(2, 2), pad=(3, 3),
                              no_bias=True, name='conv0')
    body = mx.sym.BatchNorm(data=body, fix_gamma=False, name='bn0')
    body = mx.sym.Activation(data=body, act_type='relu', name='relu0')
    body = mx.sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2),
                          pad=(1, 1), pool_type='max')

    for stage, n_units in enumerate(units):
        stride = (1, 1) if stage == 0 else (2, 2)
        body = residual_unit(body, filters[stage + 1], stride, False,
                             'stage%d_unit1' % (stage + 1), bottleneck)
        for unit in range(n_units - 1):
            body = residual_unit(body, filters[stage + 1], (1, 1), True,
                                 'stage%d_unit%d' % (stage + 1, unit + 2),
                                 bottleneck)
    bn1 = mx.sym.BatchNorm(data=body, fix_gamma=False, name='bn1')
    relu1 = mx.sym.Activation(data=bn1, act_type='relu', name='relu1')
    pool1 = mx.sym.Pooling(data=relu1, global_pool=True, kernel=(7, 7),
                           pool_type='avg', name='pool1')
    flat = mx.sym.Flatten(data=pool1)
    fc1 = mx.sym.FullyConnected(data=flat, num_hidden=num_classes, name='fc1')
    if dtype == 'float16':
        fc1 = mx.sym.Cast(data=fc1, dtype='float32')
    return mx.sym.SoftmaxOutput(data=fc1, name='softmax')
