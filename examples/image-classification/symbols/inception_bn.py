"""Symbolic Inception-BN (BN-GoogLeNet) builder.

Mirrors the role of example/image-classification/symbols/inception-bn.py
in the reference (Ioffe & Szegedy, arXiv:1502.03167): the 224px network
is a 7x7 stem, a 1x1/3x3 second stage, then ten inception blocks in a
config table; small images (<=28px) get the compact CIFAR variant. The
block layout is expressed as a spec table rather than unrolled calls;
written against the mxnet_tpu Symbol API.
"""
import mxnet_tpu as mx

_EPS = 1e-10 + 1e-5
_MOM = 0.9

# 224px trunk: (name, kind, spec)
#   'mix'  spec = (n1x1, red3x3, n3x3, red_d3x3, n_d3x3, pool_type, n_proj)
#   'down' spec = (red3x3, n3x3, red_d3x3, n_d3x3)  — stride-2, +maxpool branch
_BLOCKS_224 = [
    ('3a', 'mix', (64, 64, 64, 64, 96, 'avg', 32)),
    ('3b', 'mix', (64, 64, 96, 64, 96, 'avg', 64)),
    ('3c', 'down', (128, 160, 64, 96)),
    ('4a', 'mix', (224, 64, 96, 96, 128, 'avg', 128)),
    ('4b', 'mix', (192, 96, 128, 96, 128, 'avg', 128)),
    ('4c', 'mix', (160, 128, 160, 128, 160, 'avg', 128)),
    ('4d', 'mix', (96, 128, 192, 160, 192, 'avg', 128)),
    ('4e', 'down', (128, 192, 192, 256)),
    ('5a', 'mix', (352, 192, 320, 160, 224, 'avg', 128)),
    ('5b', 'mix', (352, 192, 320, 192, 224, 'max', 128)),
]

# compact trunk for small images: (name, kind, spec)
#   'simple' spec = (n1x1, n3x3); 'shrink' spec = (n3x3,) — stride-2 conv+pool
_BLOCKS_SMALL = [
    ('in3a', 'simple', (32, 32)),
    ('in3b', 'simple', (32, 48)),
    ('in3c', 'shrink', (80,)),
    ('in4a', 'simple', (112, 48)),
    ('in4b', 'simple', (96, 64)),
    ('in4c', 'simple', (80, 80)),
    ('in4d', 'simple', (48, 96)),
    ('in4e', 'shrink', (96,)),
    ('in5a', 'simple', (176, 160)),
    ('in5b', 'simple', (176, 160)),
]


def _unit(x, filters, kernel, name, stride=(1, 1), pad=(0, 0)):
    """conv -> BN -> relu, the paper's replacement for conv -> relu."""
    x = mx.sym.Convolution(data=x, num_filter=filters, kernel=kernel,
                           stride=stride, pad=pad, name='conv_' + name)
    x = mx.sym.BatchNorm(data=x, fix_gamma=False, eps=_EPS, momentum=_MOM,
                         name='bn_' + name)
    return mx.sym.Activation(data=x, act_type='relu', name='relu_' + name)


def _branch3x3(x, red, out, name, double, stride=(1, 1)):
    """1x1 reduce then one (or two, 'double') 3x3 convs."""
    tag = ('%s_double_3x3' if double else '%s_3x3') % name
    b = _unit(x, red, (1, 1), tag + '_reduce')
    if double:
        b = _unit(b, out, (3, 3), tag + '_0', pad=(1, 1))
        return _unit(b, out, (3, 3), tag + '_1', stride=stride, pad=(1, 1))
    return _unit(b, out, (3, 3), tag, stride=stride, pad=(1, 1))


def _block(x, name, kind, spec):
    if kind == 'mix':
        n1, r3, n3, rd, nd, pool, proj = spec
        p = mx.sym.Pooling(data=x, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                           pool_type=pool,
                           name='%s_pool_%s_pool' % (pool, name))
        parts = [_unit(x, n1, (1, 1), name + '_1x1'),
                 _branch3x3(x, r3, n3, name, double=False),
                 _branch3x3(x, rd, nd, name, double=True),
                 _unit(p, proj, (1, 1), name + '_proj')]
    elif kind == 'down':
        r3, n3, rd, nd = spec
        parts = [_branch3x3(x, r3, n3, name, double=False, stride=(2, 2)),
                 _branch3x3(x, rd, nd, name, double=True, stride=(2, 2)),
                 mx.sym.Pooling(data=x, kernel=(3, 3), stride=(2, 2),
                                pad=(1, 1), pool_type='max',
                                name='max_pool_%s_pool' % name)]
    elif kind == 'simple':
        n1, n3 = spec
        parts = [_unit(x, n1, (1, 1), name + '_1x1'),
                 _unit(x, n3, (3, 3), name + '_3x3', pad=(1, 1))]
    else:  # 'shrink'
        (n3,) = spec
        parts = [_unit(x, n3, (3, 3), name + '_conv', stride=(2, 2),
                       pad=(1, 1)),
                 mx.sym.Pooling(data=x, kernel=(3, 3), stride=(2, 2),
                                pad=(1, 1), pool_type='max',
                                name=name + '_pool')]
    return mx.sym.Concat(*parts, name='ch_concat_%s_chconcat' % name)


def get_symbol(num_classes=1000, image_shape='3,224,224', **kwargs):
    _, height, _ = (int(d) for d in image_shape.split(','))
    data = mx.sym.Variable('data')
    if height <= 28:
        body = _unit(data, 96, (3, 3), '1', pad=(1, 1))
        blocks = _BLOCKS_SMALL
    else:
        body = _unit(data, 64, (7, 7), '1', stride=(2, 2), pad=(3, 3))
        body = mx.sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2),
                              pool_type='max', name='pool_1')
        body = _unit(body, 64, (1, 1), '2_red')
        body = _unit(body, 192, (3, 3), '2', pad=(1, 1))
        body = mx.sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2),
                              pool_type='max', name='pool_2')
        blocks = _BLOCKS_224
    for name, kind, spec in blocks:
        body = _block(body, name, kind, spec)
    body = mx.sym.Pooling(data=body, kernel=(7, 7), stride=(1, 1),
                          pool_type='avg', name='global_pool')
    body = mx.sym.Flatten(data=body)
    body = mx.sym.FullyConnected(data=body, num_hidden=num_classes)
    return mx.sym.SoftmaxOutput(data=body, name='softmax')
