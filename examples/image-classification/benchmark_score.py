"""Inference scoring throughput (images/sec) — the reference's
`benchmark_score.py` (docs/how_to/perf.md:115-146 table).

Scores model_zoo networks at several batch sizes on synthetic data with
the hybridized (fully compiled) forward.

    python benchmark_score.py --model resnet50_v1 --batch-sizes 1,32
"""
import argparse
import logging
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon.model_zoo import vision


def _symbol_forward(model, batch_size, image_size):
    """Symbol-defined networks (the reference scores inception-bn from
    symbols/, not the model zoo): bind once, return forward thunk."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from symbols.inception_bn import get_symbol
    sym = get_symbol(num_classes=1000,
                     image_shape='3,%d,%d' % (image_size, image_size))
    mod = mx.mod.Module(sym, context=mx.cpu()
                        if not mx.context.num_gpus() else mx.gpu())
    shape = (batch_size, 3, image_size, image_size)
    mod.bind(data_shapes=[('data', shape)], for_training=False)
    mod.init_params(initializer=mx.init.Xavier())
    batch = mx.io.DataBatch(data=[nd.array(np.random.standard_normal(
        shape).astype('float32'))], label=None)

    def forward():
        mod.forward(batch, is_train=False)
        return mod.get_outputs()[0]
    return forward


def score(model, batch_size, image_size=224, repeats=20):
    if model == 'inception-bn':
        forward = _symbol_forward(model, batch_size, image_size)
    else:
        net = vision.get_model(model, classes=1000)
        net.initialize(mx.init.Xavier())
        net.hybridize()
        x = nd.array(np.random.standard_normal(
            (batch_size, 3, image_size, image_size)).astype('float32'))

        def forward():
            return net(x)
    out = forward()
    out.wait_to_read()  # compile
    tic = time.time()
    for _ in range(repeats):
        out = forward()
    out.wait_to_read()
    return repeats * batch_size / (time.time() - tic)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='resnet50_v1')
    parser.add_argument('--batch-sizes', default='1,32')
    parser.add_argument('--image-size', type=int, default=224)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    for bs in (int(b) for b in args.batch_sizes.split(',')):
        ips = score(args.model, bs, args.image_size)
        logging.info('model %s batch %d: %.1f images/sec',
                     args.model, bs, ips)


if __name__ == '__main__':
    main()
