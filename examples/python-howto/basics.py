"""Python API how-to — reference example/python-howto/ (data_iter.py,
monitor_weights.py, multiple_outputs.py): a guided tour of the NDArray /
Symbol / Module fundamentals, each section self-checking.

    python basics.py
"""
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx


def section_ndarray():
    """NDArray: device arrays with numpy semantics + lazy execution."""
    a = mx.nd.ones((2, 3))
    b = mx.nd.arange(6).reshape((2, 3))
    c = (a + b * 2).asnumpy()
    np.testing.assert_allclose(c, [[1, 3, 5], [7, 9, 11]])
    # autograd on plain arrays
    x = mx.nd.array([1., 2., 3.])
    x.attach_grad()
    with mx.autograd.record():
        y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2., 4., 6.])


def section_custom_iter():
    """Reference data_iter.py: a hand-rolled DataIter."""
    class SimpleIter(mx.io.DataIter):
        def __init__(self, n_batches=4, batch_size=8):
            super().__init__(batch_size)
            self.n = n_batches
            self.i = 0
            self.provide_data = [mx.io.DataDesc('data', (batch_size, 5))]
            self.provide_label = [mx.io.DataDesc('softmax_label',
                                                 (batch_size,))]

        def reset(self):
            self.i = 0

        def next(self):
            if self.i >= self.n:
                raise StopIteration
            self.i += 1
            return mx.io.DataBatch(
                data=[mx.nd.ones((self.batch_size, 5)) * self.i],
                label=[mx.nd.zeros((self.batch_size,))])

    it = SimpleIter()
    seen = sum(1 for _ in it)
    assert seen == 4
    it.reset()
    assert float(next(iter(it)).data[0].asnumpy().mean()) == 1.0


def section_multiple_outputs():
    """Reference multiple_outputs.py: Group symbols expose every head."""
    data = mx.sym.Variable('data')
    fc = mx.sym.FullyConnected(data, num_hidden=4, name='fc')
    out = mx.sym.Group([mx.sym.softmax(fc), mx.sym.BlockGrad(fc)])
    exe = out.simple_bind(mx.cpu(), data=(2, 3))
    exe.arg_dict['data'][:] = np.ones((2, 3), np.float32)
    exe.forward()
    assert len(exe.outputs) == 2
    np.testing.assert_allclose(exe.outputs[0].asnumpy().sum(axis=1),
                               [1., 1.], rtol=1e-5)


def section_monitor():
    """Reference monitor_weights.py: Monitor taps executor tensors."""
    mon = mx.monitor.Monitor(1, stat_func=lambda d: mx.nd.array(
        [float(mx.nd.abs(d).mean().asscalar())]),
        pattern='.*weight')
    data = mx.sym.Variable('data')
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(data, num_hidden=2, name='fc'),
        name='softmax')
    exe = net.simple_bind(mx.cpu(), data=(4, 3), softmax_label=(4,))
    mon.install(exe)
    exe.arg_dict['data'][:] = np.random.randn(4, 3)
    mon.tic()
    exe.forward(is_train=True)
    stats = mon.toc()
    seen = [name for (_, name, _) in stats]
    assert any('weight' in n for n in seen), seen


def main():
    logging.basicConfig(level=logging.INFO)
    for fn in (section_ndarray, section_custom_iter,
               section_multiple_outputs, section_monitor):
        fn()
        logging.info('%s OK', fn.__name__)
    print('python_howto: 4 sections OK')


if __name__ == '__main__':
    main()
