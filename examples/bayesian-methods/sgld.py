"""Stochastic Gradient Langevin Dynamics — reference example/
bayesian-methods/sgld.ipynb (Welling & Teh 2011): the 'sgld' optimizer
injects N(0, sqrt(lr)) noise into each SGD step, turning optimization
into posterior sampling. Hermetic: Bayesian linear regression, whose
exact Gaussian posterior the SGLD iterates must reproduce.

    python sgld.py --steps 4000
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx

DIM = 3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--steps', type=int, default=4000)
    ap.add_argument('--burnin', type=int, default=1000)
    ap.add_argument('--samples', type=int, default=256)
    ap.add_argument('--lr', type=float, default=1e-3)
    ap.add_argument('--noise', type=float, default=0.5,
                    help='observation noise std')
    ap.add_argument('--tol-mean', type=float, default=0.15)
    ap.add_argument('--tol-std', type=float, default=0.5,
                    help='relative tolerance on posterior std')
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(9)

    rng = np.random.RandomState(0)
    w_true = rng.randn(DIM).astype(np.float32)
    X = rng.randn(args.samples, DIM).astype(np.float32)
    y = X @ w_true + args.noise * rng.randn(args.samples).astype(np.float32)

    # exact posterior: w ~ N(mu, S), S = (X'X/s^2 + I)^-1 (unit prior)
    s2 = args.noise ** 2
    S = np.linalg.inv(X.T @ X / s2 + np.eye(DIM))
    mu = S @ (X.T @ y) / s2

    # SGLD over the unnormalized log posterior. The optimizer expects
    # the gradient of the SUMMED negative log posterior.
    w = mx.nd.zeros((DIM,))
    opt = mx.optimizer.create('sgld', learning_rate=args.lr,
                              rescale_grad=1.0, wd=0.0)
    updater = mx.optimizer.get_updater(opt)
    chain = []
    Xn, yn = mx.nd.array(X), mx.nd.array(y)
    for step in range(args.steps):
        resid = mx.nd.dot(Xn, w) - yn
        grad = mx.nd.dot(Xn.T, resid) / s2 + w   # -dlogp/dw (unit prior)
        updater(0, grad, w)
        if step >= args.burnin:
            chain.append(w.asnumpy().copy())
        if step % 1000 == 0:
            logging.info('step %d w %s', step, w.asnumpy())

    chain = np.stack(chain)
    emp_mu, emp_std = chain.mean(0), chain.std(0)
    logging.info('posterior mean: exact %s  sgld %s', mu, emp_mu)
    logging.info('posterior std : exact %s  sgld %s', np.sqrt(np.diag(S)),
                 emp_std)
    assert np.abs(emp_mu - mu).max() < args.tol_mean, (emp_mu, mu)
    rel = np.abs(emp_std - np.sqrt(np.diag(S))) / np.sqrt(np.diag(S))
    assert rel.max() < args.tol_std, (emp_std, np.sqrt(np.diag(S)))
    print('sgld: mean_err=%.4f std_rel_err=%.3f'
          % (np.abs(emp_mu - mu).max(), rel.max()))


if __name__ == '__main__':
    main()
