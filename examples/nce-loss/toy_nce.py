"""Noise-contrastive estimation — reference example/nce-loss/toy_nce.py:
train a many-class softmax-like model with NCE (binary logistic
discrimination of the true class against k sampled noise classes)
instead of a full softmax, then verify the full-softmax accuracy the
cheap objective induces.

    python toy_nce.py --epochs 15
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

NCLASS = 256      # large output vocabulary (what makes NCE worth it)
DIM = 32
K = 8             # noise samples per example


class NCEModel(gluon.Block):
    """Feature trunk + per-class output embeddings and biases; NCE
    scores are dot(feature, class_embedding) + bias for just the
    sampled classes (reference nce.py nce_loss structure)."""

    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.trunk = nn.Dense(64, activation='relu')
            self.feat = nn.Dense(32)
            self.class_embed = nn.Embedding(NCLASS, 32)
            self.class_bias = nn.Embedding(NCLASS, 1)

    def score(self, x, classes):
        """classes: (N, 1+K) int — scores for true + noise classes."""
        f = self.feat(self.trunk(x))                    # (N, 32)
        w = self.class_embed(classes)                   # (N, 1+K, 32)
        b = self.class_bias(classes).reshape((0, -1))   # (N, 1+K)
        return (w * f.expand_dims(axis=1)).sum(axis=-1) + b

    def full_scores(self, x):
        f = self.feat(self.trunk(x))                    # (N, 32)
        allw = self.class_embed.weight.data()           # (C, 32)
        allb = self.class_bias.weight.data().reshape((-1,))
        return mx.nd.dot(f, allw.T) + allb


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=15)
    ap.add_argument('--samples', type=int, default=2048)
    ap.add_argument('--batch-size', type=int, default=128)
    ap.add_argument('--lr', type=float, default=5e-3)
    ap.add_argument('--min-acc', type=float, default=0.8)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(5)

    rng = np.random.RandomState(6)
    centers = rng.randn(NCLASS, DIM).astype(np.float32) * 2.0
    lab = rng.randint(0, NCLASS, args.samples)
    x = (centers[lab] + 0.3 * rng.randn(args.samples, DIM)).astype(np.float32)
    xte_lab = rng.randint(0, NCLASS, 512)
    xte = (centers[xte_lab] + 0.3 * rng.randn(512, DIM)).astype(np.float32)

    net = NCEModel()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    for epoch in range(args.epochs):
        perm = rng.permutation(len(x))
        tot = 0.0
        for i in range(0, len(x), args.batch_size):
            idx = perm[i:i + args.batch_size]
            n = len(idx)
            noise = rng.randint(0, NCLASS, size=(n, K))
            classes = np.concatenate([lab[idx][:, None], noise], axis=1)
            target = np.zeros((n, 1 + K), np.float32)
            target[:, 0] = 1.0
            data = mx.nd.array(x[idx])
            cls = mx.nd.array(classes.astype(np.float32))
            with autograd.record():
                scores = net.score(data, cls)
                loss = bce(scores, mx.nd.array(target))
            loss.backward()
            trainer.step(n)
            tot += float(loss.mean().asscalar()) * n
        logging.info('epoch %d nce loss %.4f', epoch, tot / len(x))

    pred = net.full_scores(mx.nd.array(xte)).asnumpy().argmax(axis=1)
    acc = float((pred == xte_lab).mean())
    logging.info('full-softmax accuracy from NCE training: %.3f', acc)
    assert acc >= args.min_acc, 'NCE training failed: %.3f' % acc
    print('toy_nce: acc=%.3f' % acc)


if __name__ == '__main__':
    main()
