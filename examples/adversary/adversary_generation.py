"""FGSM adversarial examples — reference example/adversary/adversary_generation.ipynb.

Trains a small MLP classifier, then perturbs test inputs along the sign
of the input gradient (Goodfellow et al., FGSM) and measures the
accuracy collapse. Hermetic: well-separated Gaussian blobs stand in for
MNIST so the clean model is near-perfect and the adversarial direction
is exactly learnable.

    python adversary_generation.py --epochs 10 --epsilon 0.25
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

NCLASS = 5
DIM = 256


def blobs(rng, n, centers):
    labels = rng.randint(0, NCLASS, size=n)
    x = centers[labels] + 0.25 * rng.randn(n, DIM).astype(np.float32)
    return x.astype(np.float32), labels.astype(np.float32)


def accuracy(net, x, y):
    pred = net(mx.nd.array(x)).asnumpy().argmax(axis=1)
    return float((pred == y).mean())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=10)
    ap.add_argument('--samples', type=int, default=512)
    ap.add_argument('--batch-size', type=int, default=64)
    ap.add_argument('--epsilon', type=float, default=0.25)
    ap.add_argument('--lr', type=float, default=0.1)
    ap.add_argument('--min-drop', type=float, default=0.2,
                    help='required clean-vs-adversarial accuracy drop')
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(7)
    centers = rng.randn(NCLASS, DIM).astype(np.float32) * 0.3
    xtr, ytr = blobs(rng, args.samples, centers)
    xte, yte = blobs(rng, args.samples // 2, centers)

    net = nn.Sequential()
    with net.name_scope():
        net.add(nn.Dense(64, activation='relu'), nn.Dense(NCLASS))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': args.lr, 'momentum': 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        perm = rng.permutation(len(xtr))
        tot = 0.0
        for i in range(0, len(xtr), args.batch_size):
            idx = perm[i:i + args.batch_size]
            data = mx.nd.array(xtr[idx])
            label = mx.nd.array(ytr[idx])
            with autograd.record():
                loss = loss_fn(net(data), label)
            loss.backward()
            trainer.step(len(idx))
            tot += float(loss.mean().asscalar()) * len(idx)
        logging.info('epoch %d loss %.4f', epoch, tot / len(xtr))

    clean_acc = accuracy(net, xte, yte)

    # FGSM: x_adv = x + eps * sign(dL/dx)
    data = mx.nd.array(xte)
    label = mx.nd.array(yte)
    data.attach_grad()
    with autograd.record():
        loss = loss_fn(net(data), label)
    loss.backward()
    x_adv = data + args.epsilon * mx.nd.sign(data.grad)
    adv_acc = accuracy(net, x_adv.asnumpy(), yte)

    drop = clean_acc - adv_acc
    logging.info('clean acc %.3f  adversarial acc %.3f  drop %.3f',
                 clean_acc, adv_acc, drop)
    assert clean_acc > 0.9, 'clean model failed to train: %.3f' % clean_acc
    assert drop >= args.min_drop, (
        'FGSM attack too weak: drop %.3f < %.3f' % (drop, args.min_drop))
    print('adversary: clean=%.3f adv=%.3f drop=%.3f' %
          (clean_acc, adv_acc, drop))


if __name__ == '__main__':
    main()
