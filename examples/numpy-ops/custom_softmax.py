"""CustomOp softmax — reference example/numpy-ops/custom_softmax.py.

Defines softmax cross-entropy as a python CustomOp (numpy forward /
backward, registered via mx.operator.register) and trains an MLP with
it through the Module API — demonstrating the legacy python-operator
bridge end to end. Hermetic synthetic blobs stand in for MNIST.

    python custom_softmax.py --epochs 8
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import mxnet_tpu as mx

NCLASS = 6
DIM = 24


class Softmax(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        y = np.exp(x - x.max(axis=1).reshape((x.shape[0], 1)))
        y /= y.sum(axis=1).reshape((x.shape[0], 1))
        self.assign(out_data[0], req[0], mx.nd.array(y))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        lab = in_data[1].asnumpy().ravel().astype(np.int64)
        y = out_data[0].asnumpy().copy()
        y[np.arange(lab.shape[0]), lab] -= 1.0
        self.assign(in_grad[0], req[0], mx.nd.array(y))


@mx.operator.register('example_softmax')
class SoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super(SoftmaxProp, self).__init__(need_top_grad=False)

    def list_arguments(self):
        return ['data', 'label']

    def list_outputs(self):
        return ['output']

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = (in_shape[0][0],)
        return [data_shape, label_shape], [data_shape], []

    def create_operator(self, ctx, shapes, dtypes):
        return Softmax()


def blobs(rng, n, centers):
    labels = rng.randint(0, NCLASS, size=n)
    x = centers[labels] + 0.4 * rng.randn(n, DIM).astype(np.float32)
    return x.astype(np.float32), labels.astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=8)
    ap.add_argument('--samples', type=int, default=512)
    ap.add_argument('--batch-size', type=int, default=64)
    ap.add_argument('--lr', type=float, default=0.02,
                    help='the CustomOp backward emits unnormalized batch '
                         'gradients (reference custom_softmax.py), so keep '
                         'lr small')
    ap.add_argument('--min-acc', type=float, default=0.9)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(11)
    centers = rng.randn(NCLASS, DIM).astype(np.float32) * 2.0
    xtr, ytr = blobs(rng, args.samples, centers)
    xte, yte = blobs(rng, args.samples // 2, centers)
    train = mx.io.NDArrayIter(xtr, ytr, args.batch_size, shuffle=True,
                              label_name='softmax_label')
    val = mx.io.NDArrayIter(xte, yte, args.batch_size,
                            label_name='softmax_label')

    data = mx.sym.Variable('data')
    label = mx.sym.Variable('softmax_label')
    fc1 = mx.sym.FullyConnected(data=data, num_hidden=64, name='fc1')
    act1 = mx.sym.Activation(data=fc1, act_type='relu', name='relu1')
    fc2 = mx.sym.FullyConnected(data=act1, num_hidden=NCLASS, name='fc2')
    net = mx.sym.Custom(data=fc2, label=label, op_type='example_softmax',
                        name='softmax')

    mod = mx.mod.Module(symbol=net, context=mx.current_context(),
                        label_names=('softmax_label',))
    mod.fit(train, eval_data=val, eval_metric='acc', optimizer='sgd',
            optimizer_params={'learning_rate': args.lr, 'momentum': 0.9},
            num_epoch=args.epochs)
    score = dict(mod.score(val, ['acc']))
    logging.info('validation acc %.3f', score['accuracy'])
    assert score['accuracy'] >= args.min_acc, score
    print('custom_softmax: acc=%.3f' % score['accuracy'])


if __name__ == '__main__':
    main()
