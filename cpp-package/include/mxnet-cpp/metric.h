/*
 * metric.h — C++ evaluation metrics.
 *
 * Reference: cpp-package/include/mxnet-cpp/metric.h (EvalMetric base +
 * Accuracy/LogLoss/MAE/MSE/RMSE over host-fetched predictions).
 */
#ifndef MXNET_TPU_CPP_METRIC_H_
#define MXNET_TPU_CPP_METRIC_H_

#include <algorithm>
#include <cmath>
#include <string>
#include <vector>

#include "MxNetCpp.h"

namespace mxnet {
namespace cpp {

class EvalMetric {
 public:
  explicit EvalMetric(const std::string &name, int num = 0)
      : name_(name), num_(num) {}
  virtual ~EvalMetric() {}
  virtual void Update(const NDArray &labels,
                      const NDArray &preds) = 0;
  void Reset() {
    num_inst = 0;
    sum_metric = 0.0f;
  }
  float Get() const { return sum_metric / std::max<size_t>(num_inst, 1); }
  void GetNameValue() const {}

 protected:
  std::string name_;
  int num_;
  float sum_metric = 0.0f;
  size_t num_inst = 0;

  static void CheckLabelShapes(const NDArray &labels, const NDArray &preds,
                               bool strict = false) {
    if (strict && labels.Size() != preds.Size())
      throw std::runtime_error("label/pred size mismatch");
  }
};

class Accuracy : public EvalMetric {
 public:
  Accuracy() : EvalMetric("accuracy") {}

  void Update(const NDArray &labels,
              const NDArray &preds) override {
    std::vector<float> lab = labels.AsVector();
    std::vector<float> prd = preds.AsVector();
    Shape ps = preds.GetShape();
    size_t batch = ps[0];
    if (lab.size() != batch)
      throw std::runtime_error("Accuracy: labels must be (batch,)");
    size_t ncls = prd.size() / std::max<size_t>(batch, 1);
    for (size_t i = 0; i < batch; ++i) {
      long cls = static_cast<long>(lab[i]);
      if (cls < 0)
        continue;  /* ignore-label convention (-1) */
      size_t best = 0;
      for (size_t c = 1; c < ncls; ++c)
        if (prd[i * ncls + c] > prd[i * ncls + best]) best = c;
      sum_metric += (static_cast<size_t>(cls) == best) ? 1.0f : 0.0f;
      num_inst += 1;
    }
  }
};

class LogLoss : public EvalMetric {
 public:
  LogLoss() : EvalMetric("logloss") {}

  void Update(const NDArray &labels,
              const NDArray &preds) override {
    const float eps = 1e-15f;
    std::vector<float> lab = labels.AsVector();
    std::vector<float> prd = preds.AsVector();
    Shape ps = preds.GetShape();
    size_t batch = ps[0];
    if (lab.size() != batch)
      throw std::runtime_error("LogLoss: labels must be (batch,)");
    size_t ncls = prd.size() / std::max<size_t>(batch, 1);
    for (size_t i = 0; i < batch; ++i) {
      long cls = static_cast<long>(lab[i]);
      if (cls < 0 || cls >= static_cast<long>(ncls))
        continue;  /* ignore-label convention (-1) / malformed labels */
      float p = prd[i * ncls + static_cast<size_t>(cls)];
      sum_metric += -std::log(std::max(p, eps));
      num_inst += 1;
    }
  }
};

class MAE : public EvalMetric {
 public:
  MAE() : EvalMetric("mae") {}

  void Update(const NDArray &labels,
              const NDArray &preds) override {
    CheckLabelShapes(labels, preds, true);
    std::vector<float> lab = labels.AsVector();
    std::vector<float> prd = preds.AsVector();
    for (size_t i = 0; i < prd.size(); ++i)
      sum_metric += std::fabs(lab[i] - prd[i]);
    num_inst += prd.size();
  }
};

class MSE : public EvalMetric {
 public:
  MSE() : EvalMetric("mse") {}

  void Update(const NDArray &labels,
              const NDArray &preds) override {
    CheckLabelShapes(labels, preds, true);
    std::vector<float> lab = labels.AsVector();
    std::vector<float> prd = preds.AsVector();
    for (size_t i = 0; i < prd.size(); ++i)
      sum_metric += (lab[i] - prd[i]) * (lab[i] - prd[i]);
    num_inst += prd.size();
  }
};

class RMSE : public EvalMetric {
 public:
  RMSE() : EvalMetric("rmse") {}

  void Update(const NDArray &labels,
              const NDArray &preds) override {
    CheckLabelShapes(labels, preds, true);
    std::vector<float> lab = labels.AsVector();
    std::vector<float> prd = preds.AsVector();
    float sq = 0.0f;
    for (size_t i = 0; i < prd.size(); ++i)
      sq += (lab[i] - prd[i]) * (lab[i] - prd[i]);
    sum_metric += std::sqrt(sq / std::max<size_t>(prd.size(), 1));
    num_inst += 1;
  }
};

}  // namespace cpp
}  // namespace mxnet

#endif  // MXNET_TPU_CPP_METRIC_H_
