/*
 * MxNetCpp.h — header-only C++ frontend over the C ABI (N20).
 *
 * Reference: cpp-package/include/mxnet-cpp/ (NDArray/Symbol/Executor/
 * KVStore/Optimizer wrappers over c_api.h, ~3k LoC across 20 headers).
 * Single-header here: the C ABI already carries the graph machinery, so
 * the C++ layer is RAII handles + ergonomic operators, which is all the
 * reference's was.
 */
#ifndef MXNET_TPU_CPP_MXNETCPP_H_
#define MXNET_TPU_CPP_MXNETCPP_H_

#include <cstring>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

#include "../../../include/mxnet_tpu/c_api.h"

namespace mxnet {
namespace cpp {

inline void Check(int rc) {
  if (rc != 0) throw std::runtime_error(MXGetLastError());
}

/* reference: cpp-package/include/mxnet-cpp/base.h DeviceType */
enum class DeviceType : int { kCPU = 1, kGPU = 2, kTPU = 6 };

struct Context {
  DeviceType type;
  int id;
  Context(DeviceType t = DeviceType::kCPU, int i = 0) : type(t), id(i) {}
  static Context cpu(int id = 0) { return Context(DeviceType::kCPU, id); }
  static Context tpu(int id = 0) { return Context(DeviceType::kTPU, id); }
  static Context gpu(int id = 0) { return Context(DeviceType::kGPU, id); }
};

struct Shape : public std::vector<mx_uint> {
  using std::vector<mx_uint>::vector;
};

/* reference: op_map.h — creator lookup table built once */
class OpMap {
 public:
  static AtomicSymbolCreator Get(const std::string &name) {
    static std::map<std::string, AtomicSymbolCreator> *map_ = [] {
      auto *m = new std::map<std::string, AtomicSymbolCreator>();
      mx_uint n;
      AtomicSymbolCreator *creators;
      Check(MXSymbolListAtomicSymbolCreators(&n, &creators));
      for (mx_uint i = 0; i < n; ++i) {
        const char *cname;
        Check(MXSymbolGetAtomicSymbolName(creators[i], &cname));
        (*m)[cname] = creators[i];
      }
      return m;
    }();
    auto it = map_->find(name);
    if (it == map_->end())
      throw std::runtime_error("unknown operator " + name);
    return it->second;
  }
};

class NDArray {
 public:
  NDArray() : handle_(nullptr) {}
  explicit NDArray(NDArrayHandle h) : handle_(h) {}
  NDArray(const Shape &shape, const Context &ctx, int dtype = 0) {
    NDArrayHandle h;
    Check(MXNDArrayCreateEx(shape.data(), (mx_uint)shape.size(),
                            (int)ctx.type, ctx.id, 0, dtype, &h));
    handle_ = h;
  }
  NDArray(const std::vector<float> &data, const Shape &shape,
          const Context &ctx) : NDArray(shape, ctx) {
    SyncCopyFromCPU(data.data(), data.size());
  }
  NDArray(const NDArray &) = delete;
  NDArray &operator=(const NDArray &) = delete;
  NDArray(NDArray &&o) noexcept : handle_(o.handle_) { o.handle_ = nullptr; }
  NDArray &operator=(NDArray &&o) noexcept {
    if (this != &o) { Free(); handle_ = o.handle_; o.handle_ = nullptr; }
    return *this;
  }
  ~NDArray() { Free(); }

  void SyncCopyFromCPU(const float *data, size_t size) {
    Check(MXNDArraySyncCopyFromCPU(handle_, data, size));
  }
  void SyncCopyToCPU(float *data, size_t size) const {
    Check(MXNDArraySyncCopyToCPU(handle_, data, size));
  }
  std::vector<float> AsVector() const {
    std::vector<float> out(Size());
    SyncCopyToCPU(out.data(), out.size());
    return out;
  }
  Shape GetShape() const {
    mx_uint ndim;
    const mx_uint *dims;
    Check(MXNDArrayGetShape(handle_, &ndim, &dims));
    return Shape(dims, dims + ndim);
  }
  size_t Size() const {
    size_t n = 1;
    for (auto d : GetShape()) n *= d;
    return n;
  }
  int GetDType() const {
    int dt;
    Check(MXNDArrayGetDType(handle_, &dt));
    return dt;
  }
  static void WaitAll() { Check(MXNDArrayWaitAll()); }

  NDArrayHandle GetHandle() const { return handle_; }

 private:
  void Free() { if (handle_) MXNDArrayFree(handle_); }
  NDArrayHandle handle_;
};

class Symbol {
 public:
  Symbol() : handle_(nullptr) {}
  explicit Symbol(SymbolHandle h) : handle_(h) {}
  static Symbol Variable(const std::string &name) {
    SymbolHandle h;
    Check(MXSymbolCreateVariable(name.c_str(), &h));
    return Symbol(h);
  }
  static Symbol Load(const std::string &fname) {
    SymbolHandle h;
    Check(MXSymbolCreateFromFile(fname.c_str(), &h));
    return Symbol(h);
  }
  static Symbol FromJSON(const std::string &json) {
    SymbolHandle h;
    Check(MXSymbolCreateFromJSON(json.c_str(), &h));
    return Symbol(h);
  }
  /* reference Operator::CreateSymbol — atomic create + compose */
  static Symbol Create(const std::string &op, const std::string &name,
                       const std::vector<std::string> &param_keys,
                       const std::vector<std::string> &param_vals,
                       const std::vector<std::string> &input_keys,
                       const std::vector<const Symbol *> &inputs) {
    std::vector<const char *> pk, pv, ik;
    for (auto &s : param_keys) pk.push_back(s.c_str());
    for (auto &s : param_vals) pv.push_back(s.c_str());
    bool positional = true;
    for (auto &s : input_keys) {
      ik.push_back(s.c_str());
      if (!s.empty()) positional = false;
    }
    std::vector<SymbolHandle> ih;
    for (auto *s : inputs) ih.push_back(s->GetHandle());
    SymbolHandle h;
    Check(MXSymbolCreateAtomicSymbol(OpMap::Get(op), (mx_uint)pk.size(),
                                     pk.data(), pv.data(), &h));
    /* all-empty keys = positional compose (variadic ops) */
    Check(MXSymbolCompose(h, name.c_str(), (mx_uint)ih.size(),
                          positional ? nullptr : ik.data(), ih.data()));
    return Symbol(h);
  }

  /* copyable via MXSymbolCopy (the reference's Symbol is a shared
   * handle; deep copy preserves the same value semantics here) */
  Symbol(const Symbol &o) : handle_(nullptr) {
    if (o.handle_) {
      SymbolHandle h;
      Check(MXSymbolCopy(o.handle_, &h));
      handle_ = h;
    }
  }
  Symbol &operator=(const Symbol &o) {
    if (this != &o) {
      Symbol tmp(o);
      std::swap(handle_, tmp.handle_);
    }
    return *this;
  }
  Symbol(Symbol &&o) noexcept : handle_(o.handle_) { o.handle_ = nullptr; }
  Symbol &operator=(Symbol &&o) noexcept {
    if (this != &o) { Free(); handle_ = o.handle_; o.handle_ = nullptr; }
    return *this;
  }
  ~Symbol() { Free(); }

  std::vector<std::string> ListArguments() const {
    return StrList(&MXSymbolListArguments);
  }
  std::vector<std::string> ListOutputs() const {
    return StrList(&MXSymbolListOutputs);
  }
  std::string ToJSON() const {
    const char *json;
    Check(MXSymbolSaveToJSON(handle_, &json));
    return json;
  }
  void Save(const std::string &fname) const {
    Check(MXSymbolSaveToFile(handle_, fname.c_str()));
  }
  SymbolHandle GetHandle() const { return handle_; }

 private:
  void Free() { if (handle_) MXSymbolFree(handle_); }
  std::vector<std::string> StrList(
      int (*fn)(SymbolHandle, mx_uint *, const char ***)) const {
    mx_uint n;
    const char **strs;
    Check(fn(handle_, &n, &strs));
    return std::vector<std::string>(strs, strs + n);
  }
  SymbolHandle handle_;
};

/* reference: operator.h — named-parameter builder over Symbol::Create */
class Operator {
 public:
  explicit Operator(const std::string &op) : op_(op) {}
  Operator &SetParam(const std::string &k, const std::string &v) {
    param_keys_.push_back(k);
    param_vals_.push_back(v);
    return *this;
  }
  Operator &SetParam(const std::string &k, const char *v) {
    return SetParam(k, std::string(v));
  }
  template <typename T>
  Operator &SetParam(const std::string &k, const T &v) {
    return SetParam(k, std::to_string(v));
  }
  Operator &SetInput(const std::string &k, const Symbol &s) {
    input_keys_.push_back(k);
    inputs_.push_back(&s);
    return *this;
  }
  Symbol CreateSymbol(const std::string &name = "") {
    return Symbol::Create(op_, name, param_keys_, param_vals_, input_keys_,
                          inputs_);
  }

 private:
  std::string op_;
  std::vector<std::string> param_keys_, param_vals_, input_keys_;
  std::vector<const Symbol *> inputs_;
};

class Executor {
 public:
  Executor(const Symbol &symbol, const Context &ctx,
           std::vector<NDArray> *in_args,
           std::vector<NDArray> *arg_grads = nullptr,
           const std::vector<mx_uint> &grad_reqs = {}) {
    std::vector<NDArrayHandle> args, grads;
    for (auto &a : *in_args) args.push_back(a.GetHandle());
    if (arg_grads)
      for (auto &g : *arg_grads) grads.push_back(g.GetHandle());
    else
      grads.assign(args.size(), nullptr);
    std::vector<mx_uint> reqs = grad_reqs;
    if (reqs.empty()) reqs.assign(args.size(), arg_grads ? 1 : 0);
    ExecutorHandle h;
    Check(MXExecutorBind(symbol.GetHandle(), (int)ctx.type, ctx.id,
                         (mx_uint)args.size(), args.data(), grads.data(),
                         reqs.data(), 0, nullptr, &h));
    handle_ = h;
  }
  Executor(const Executor &) = delete;
  Executor &operator=(const Executor &) = delete;
  ~Executor() { if (handle_) MXExecutorFree(handle_); }

  void Forward(bool is_train) {
    Check(MXExecutorForward(handle_, is_train ? 1 : 0));
  }
  void Backward(const std::vector<NDArray> &head_grads = {}) {
    std::vector<NDArrayHandle> hg;
    for (auto &g : head_grads) hg.push_back(g.GetHandle());
    Check(MXExecutorBackward(handle_, (mx_uint)hg.size(),
                             hg.empty() ? nullptr : hg.data()));
  }
  std::vector<NDArray> Outputs() {
    mx_uint n;
    NDArrayHandle *outs;
    Check(MXExecutorOutputs(handle_, &n, &outs));
    std::vector<NDArray> result;
    for (mx_uint i = 0; i < n; ++i) result.emplace_back(outs[i]);
    return result;
  }

 private:
  ExecutorHandle handle_;
};

class KVStore {
 public:
  explicit KVStore(const std::string &type = "local") {
    KVStoreHandle h;
    Check(MXKVStoreCreate(type.c_str(), &h));
    handle_ = h;
  }
  KVStore(const KVStore &) = delete;
  KVStore &operator=(const KVStore &) = delete;
  ~KVStore() { if (handle_) MXKVStoreFree(handle_); }

  void Init(int key, const NDArray &val) {
    NDArrayHandle vh = val.GetHandle();
    Check(MXKVStoreInit(handle_, 1, &key, &vh));
  }
  void Push(int key, const NDArray &val, int priority = 0) {
    NDArrayHandle vh = val.GetHandle();
    Check(MXKVStorePush(handle_, 1, &key, &vh, priority));
  }
  void Pull(int key, NDArray *out, int priority = 0) {
    NDArrayHandle oh = out->GetHandle();
    Check(MXKVStorePull(handle_, 1, &key, &oh, priority));
  }
  int GetRank() const {
    int r;
    Check(MXKVStoreGetRank(handle_, &r));
    return r;
  }
  int GetNumWorkers() const {
    int n;
    Check(MXKVStoreGetGroupSize(handle_, &n));
    return n;
  }

 private:
  KVStoreHandle handle_;
};

}  // namespace cpp
}  // namespace mxnet

#endif  /* MXNET_TPU_CPP_MXNETCPP_H_ */
