/*
 * MxDataIter.h — C++ data iterator wrapper over the C ABI.
 *
 * Reference: cpp-package/include/mxnet-cpp/MxDataIter.h (MXDataIter:
 * creator lookup by name + SetParam + Next/GetData/GetLabel). The
 * registered iterator families are served by MXListDataIters /
 * MXDataIterCreateIter.
 */
#ifndef MXNET_TPU_CPP_MXDATAITER_H_
#define MXNET_TPU_CPP_MXDATAITER_H_

#include <map>
#include <string>
#include <vector>

#include "MxNetCpp.h"

namespace mxnet {
namespace cpp {

class MXDataIter {
 public:
  explicit MXDataIter(const std::string &name) : name_(name) {}
  MXDataIter(const MXDataIter &) = delete;
  MXDataIter &operator=(const MXDataIter &) = delete;
  ~MXDataIter() { if (handle_) MXDataIterFree(handle_); }

  MXDataIter &SetParam(const std::string &k, const std::string &v) {
    params_[k] = v;
    return *this;
  }
  template <typename T>
  MXDataIter &SetParam(const std::string &k, const T &v) {
    return SetParam(k, std::to_string(v));
  }

  MXDataIter &CreateDataIter() {
    mx_uint n;
    DataIterHandle *creators;
    Check(MXListDataIters(&n, &creators));
    DataIterHandle creator = nullptr;
    for (mx_uint i = 0; i < n; ++i) {
      const char *cname, *desc, **anames, **atypes, **adescs;
      mx_uint nargs;
      Check(MXDataIterGetIterInfo(creators[i], &cname, &desc, &nargs,
                                  &anames, &atypes, &adescs));
      if (name_ == cname) creator = creators[i];
    }
    if (!creator)
      throw std::runtime_error("unknown data iter " + name_);
    std::vector<const char *> pk, pv;
    for (auto &kv : params_) {
      pk.push_back(kv.first.c_str());
      pv.push_back(kv.second.c_str());
    }
    DataIterHandle h;
    Check(MXDataIterCreateIter(creator, (mx_uint)pk.size(), pk.data(),
                               pv.data(), &h));
    handle_ = h;
    return *this;
  }

  bool Next() {
    int out;
    Check(MXDataIterNext(handle_, &out));
    return out != 0;
  }
  void BeforeFirst() { Check(MXDataIterBeforeFirst(handle_)); }
  NDArray GetData() {
    NDArrayHandle h;
    Check(MXDataIterGetData(handle_, &h));
    return NDArray(h);
  }
  NDArray GetLabel() {
    NDArrayHandle h;
    Check(MXDataIterGetLabel(handle_, &h));
    return NDArray(h);
  }
  int GetPadNum() {
    int pad;
    Check(MXDataIterGetPadNum(handle_, &pad));
    return pad;
  }
  std::vector<uint64_t> GetIndex() {
    uint64_t *idx, n;
    Check(MXDataIterGetIndex(handle_, &idx, &n));
    return std::vector<uint64_t>(idx, idx + n);
  }

 private:
  std::string name_;
  std::map<std::string, std::string> params_;
  DataIterHandle handle_ = nullptr;
};

}  // namespace cpp
}  // namespace mxnet

#endif  /* MXNET_TPU_CPP_MXDATAITER_H_ */
