/*
 * optimizer.h — C++ optimizer wrappers over the fused update ops.
 *
 * Reference: cpp-package/include/mxnet-cpp/optimizer.h (Optimizer base
 * with per-index state + OptimizerRegistry::Find("sgd"|...)). Updates
 * run through MXImperativeInvoke on the registered *_update ops — the
 * same kernels the python Optimizer family uses.
 */
#ifndef MXNET_TPU_CPP_OPTIMIZER_H_
#define MXNET_TPU_CPP_OPTIMIZER_H_

#include <cmath>
#include <cstdlib>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "MxNetCpp.h"

namespace mxnet {
namespace cpp {

class Optimizer {
 public:
  virtual ~Optimizer() {}
  Optimizer *SetParam(const std::string &k, const std::string &v) {
    params_[k] = v;
    return this;
  }
  template <typename T>
  Optimizer *SetParam(const std::string &k, const T &v) {
    return SetParam(k, std::to_string(v));
  }
  virtual void Update(int index, NDArray *weight, const NDArray &grad) = 0;

 protected:
  /* run op(weight, grad, states...) writing into weight in place;
   * `overrides` take precedence over the stored params */
  void Invoke(const std::string &op, std::vector<NDArrayHandle> ins,
              NDArrayHandle out,
              const std::map<std::string, std::string> &overrides = {}) {
    std::map<std::string, std::string> merged = params_;
    for (auto &kv : overrides) merged[kv.first] = kv.second;
    std::vector<const char *> pk, pv;
    for (auto &kv : merged) {
      pk.push_back(kv.first.c_str());
      pv.push_back(kv.second.c_str());
    }
    NDArrayHandle outs_buf[1] = {out};
    NDArrayHandle *outs = outs_buf;
    int num_out = 1;
    Check(MXImperativeInvoke(OpMap::Get(op), (int)ins.size(), ins.data(),
                             &num_out, &outs, (int)pk.size(), pk.data(),
                             pv.data()));
  }
  NDArray *State(int index, const NDArray &like, int slot = 0) {
    auto key = std::make_pair(index, slot);
    auto it = states_.find(key);
    if (it == states_.end()) {
      /* NDArray(shape, ctx) is already zero-initialized */
      it = states_.emplace(key, std::make_unique<NDArray>(
                                    like.GetShape(), Context::cpu())).first;
    }
    return it->second.get();
  }

  float ParamOr(const std::string &k, float dflt) const {
    auto it = params_.find(k);
    return it == params_.end() ? dflt : std::strtof(it->second.c_str(),
                                                    nullptr);
  }

  std::map<std::string, std::string> params_;
  std::map<std::pair<int, int>, std::unique_ptr<NDArray>> states_;
};

class SGDOptimizer : public Optimizer {
 public:
  void Update(int index, NDArray *weight, const NDArray &grad) override {
    bool has_mom = ParamOr("momentum", 0.f) != 0.f;
    if (has_mom) {
      NDArray *mom = State(index, *weight);
      Invoke("sgd_mom_update",
             {weight->GetHandle(), grad.GetHandle(), mom->GetHandle()},
             weight->GetHandle());
    } else {
      Invoke("sgd_update", {weight->GetHandle(), grad.GetHandle()},
             weight->GetHandle());
    }
  }
};

class AdamOptimizer : public Optimizer {
 public:
  void Update(int index, NDArray *weight, const NDArray &grad) override {
    NDArray *m = State(index, *weight, 0);
    NDArray *v = State(index, *weight, 1);
    /* bias correction, matching the python Adam (optimizer.py): scale
     * lr by sqrt(1-beta2^t)/(1-beta1^t) for this parameter's step t */
    int t = ++step_[index];
    float lr = ParamOr("lr", 0.001f);
    float b1 = ParamOr("beta1", 0.9f), b2 = ParamOr("beta2", 0.999f);
    lr *= std::sqrt(1.f - std::pow(b2, (float)t)) /
          (1.f - std::pow(b1, (float)t));
    Invoke("adam_update",
           {weight->GetHandle(), grad.GetHandle(), m->GetHandle(),
            v->GetHandle()},
           weight->GetHandle(), {{"lr", std::to_string(lr)}});
  }

 private:
  std::map<int, int> step_;
};

class RMSPropOptimizer : public Optimizer {
 public:
  void Update(int index, NDArray *weight, const NDArray &grad) override {
    NDArray *n = State(index, *weight);
    Invoke("rmsprop_update",
           {weight->GetHandle(), grad.GetHandle(), n->GetHandle()},
           weight->GetHandle());
  }
};

class AdaGradOptimizer : public Optimizer {
 public:
  /* reference cpp-package optimizer.h AdaGradOptimizer: host-side
   * history update (the python AdaGrad composes generic ops the same
   * way; there is no fused kernel in the registry by design) */
  void Update(int index, NDArray *weight, const NDArray &grad) override {
    NDArray *hist = State(index, *weight);
    float lr = ParamOr("lr", 0.01f);
    float eps = ParamOr("eps", 1e-7f);
    float wd = ParamOr("wd", 0.f);
    std::vector<float> w = weight->AsVector();
    std::vector<float> g = grad.AsVector();
    std::vector<float> h = hist->AsVector();
    for (size_t i = 0; i < w.size(); ++i) {
      h[i] += g[i] * g[i];
      w[i] -= lr * (g[i] / std::sqrt(h[i] + eps) + wd * w[i]);
    }
    hist->SyncCopyFromCPU(h.data(), h.size());
    weight->SyncCopyFromCPU(w.data(), w.size());
  }
};

class AdaDeltaOptimizer : public Optimizer {
 public:
  /* reference cpp-package optimizer.h AdaDeltaOptimizer (Zeiler 2012) */
  void Update(int index, NDArray *weight, const NDArray &grad) override {
    NDArray *acc_g = State(index, *weight, 0);
    NDArray *acc_d = State(index, *weight, 1);
    float rho = ParamOr("rho", 0.9f);
    float eps = ParamOr("epsilon", 1e-5f);
    float wd = ParamOr("wd", 0.f);
    std::vector<float> w = weight->AsVector();
    std::vector<float> g = grad.AsVector();
    std::vector<float> ag = acc_g->AsVector();
    std::vector<float> ad = acc_d->AsVector();
    for (size_t i = 0; i < w.size(); ++i) {
      float gi = g[i] + wd * w[i];
      ag[i] = rho * ag[i] + (1 - rho) * gi * gi;
      float delta = std::sqrt(ad[i] + eps) / std::sqrt(ag[i] + eps) * gi;
      ad[i] = rho * ad[i] + (1 - rho) * delta * delta;
      w[i] -= delta;
    }
    acc_g->SyncCopyFromCPU(ag.data(), ag.size());
    acc_d->SyncCopyFromCPU(ad.data(), ad.size());
    weight->SyncCopyFromCPU(w.data(), w.size());
  }
};

class OptimizerRegistry {
 public:
  static Optimizer *Find(const std::string &name) {
    if (name == "sgd") return new SGDOptimizer();
    if (name == "adam") return new AdamOptimizer();
    if (name == "rmsprop") return new RMSPropOptimizer();
    if (name == "adagrad") return new AdaGradOptimizer();
    if (name == "adadelta") return new AdaDeltaOptimizer();
    throw std::runtime_error("unknown optimizer " + name);
  }
};

}  // namespace cpp
}  // namespace mxnet

#endif  /* MXNET_TPU_CPP_OPTIMIZER_H_ */
