/*
 * initializer.h — C++ parameter initializers.
 *
 * Reference: cpp-package/include/mxnet-cpp/initializer.h (Initializer
 * base dispatching on parameter name + Constant/Zero/One/Uniform/
 * Normal/Bilinear/Xavier, and lr_scheduler.h's LRScheduler/
 * FactorScheduler kept here as one compact surface).
 */
#ifndef MXNET_TPU_CPP_INITIALIZER_H_
#define MXNET_TPU_CPP_INITIALIZER_H_

#include <cmath>
#include <random>
#include <string>
#include <vector>

#include "MxNetCpp.h"

namespace mxnet {
namespace cpp {

class Initializer {
 public:
  virtual ~Initializer() {}

  virtual void operator()(const std::string &name, NDArray *arr) {
    if (EndsWith(name, "weight") || EndsWith(name, "parameters"))
      InitWeight(arr);
    else if (EndsWith(name, "bias") || EndsWith(name, "beta") ||
             EndsWith(name, "moving_mean") || EndsWith(name, "mean"))
      Fill(arr, 0.0f);
    else if (EndsWith(name, "gamma") || EndsWith(name, "moving_var") ||
             EndsWith(name, "var"))
      Fill(arr, 1.0f);
    else
      InitWeight(arr);
  }

 protected:
  virtual void InitWeight(NDArray *arr) = 0;

  static bool EndsWith(const std::string &s, const std::string &suf) {
    return s.size() >= suf.size() &&
           s.compare(s.size() - suf.size(), suf.size(), suf) == 0;
  }
  static void Fill(NDArray *arr, float v) {
    std::vector<float> buf(arr->Size(), v);
    arr->SyncCopyFromCPU(buf.data(), buf.size());
  }
  static std::mt19937 &Rng() {
    static std::mt19937 rng(0);
    return rng;
  }
};

class Constant : public Initializer {
 public:
  explicit Constant(float value) : value_(value) {}

 protected:
  void InitWeight(NDArray *arr) override { Fill(arr, value_); }
  float value_;
};

class Zero : public Constant {
 public:
  Zero() : Constant(0.0f) {}
};

class One : public Constant {
 public:
  One() : Constant(1.0f) {}
};

class Uniform : public Initializer {
 public:
  explicit Uniform(float scale) : lo_(-scale), hi_(scale) {}
  Uniform(float lo, float hi) : lo_(lo), hi_(hi) {}

 protected:
  void InitWeight(NDArray *arr) override {
    std::uniform_real_distribution<float> d(lo_, hi_);
    std::vector<float> buf(arr->Size());
    for (auto &v : buf) v = d(Rng());
    arr->SyncCopyFromCPU(buf.data(), buf.size());
  }
  float lo_, hi_;
};

class Normal : public Initializer {
 public:
  Normal(float mu, float sigma) : mu_(mu), sigma_(sigma) {}

 protected:
  void InitWeight(NDArray *arr) override {
    std::normal_distribution<float> d(mu_, sigma_);
    std::vector<float> buf(arr->Size());
    for (auto &v : buf) v = d(Rng());
    arr->SyncCopyFromCPU(buf.data(), buf.size());
  }
  float mu_, sigma_;
};

class Bilinear : public Initializer {
 public:
  Bilinear() {}

 protected:
  /* upsampling-deconv kernel (reference initializer.h Bilinear) */
  void InitWeight(NDArray *arr) override {
    Shape shape = arr->GetShape();
    std::vector<float> buf(arr->Size());
    int width = shape[shape.size() - 1];
    int fi = (width + 1) / 2;
    float f = static_cast<float>(fi);
    float c = (2 * f - 1 - fi % 2) / (2.0f * f);
    for (size_t i = 0; i < buf.size(); ++i) {
      float x = i % width;
      float y = (i / width) % shape[shape.size() - 2];
      buf[i] = (1 - std::fabs(x / f - c)) * (1 - std::fabs(y / f - c));
    }
    arr->SyncCopyFromCPU(buf.data(), buf.size());
  }
};

class Xavier : public Initializer {
 public:
  enum RandType { gaussian, uniform };
  enum FactorType { avg, in, out };
  explicit Xavier(RandType rand_type = gaussian,
                  FactorType factor_type = avg, float magnitude = 3)
      : rand_type_(rand_type), factor_type_(factor_type),
        magnitude_(magnitude) {}

 protected:
  void InitWeight(NDArray *arr) override {
    Shape shape = arr->GetShape();
    float hw = 1.0f;
    for (size_t i = 2; i < shape.size(); ++i) hw *= shape[i];
    float fan_in = (shape.size() > 1 ? shape[1] : shape[0]) * hw;
    float fan_out = shape[0] * hw;
    float factor = fan_in;
    if (factor_type_ == avg) factor = (fan_in + fan_out) / 2.0f;
    if (factor_type_ == out) factor = fan_out;
    float scale = std::sqrt(magnitude_ / std::max(factor, 1.0f));
    std::vector<float> buf(arr->Size());
    if (rand_type_ == uniform) {
      std::uniform_real_distribution<float> d(-scale, scale);
      for (auto &v : buf) v = d(Rng());
    } else {
      std::normal_distribution<float> d(0.0f, scale);
      for (auto &v : buf) v = d(Rng());
    }
    arr->SyncCopyFromCPU(buf.data(), buf.size());
  }
  RandType rand_type_;
  FactorType factor_type_;
  float magnitude_;
};

/* -- learning-rate schedules (reference lr_scheduler.h) -------------- */

class LRScheduler {
 public:
  explicit LRScheduler(float base_lr = 0.01f) : base_lr_(base_lr) {}
  virtual ~LRScheduler() {}
  void SetLR(float lr) { base_lr_ = lr; }
  virtual float GetLR(unsigned num_update) = 0;

 protected:
  float base_lr_;
};

class FactorScheduler : public LRScheduler {
 public:
  explicit FactorScheduler(int step, float factor = 1.0f,
                           float stop_factor_lr = 1e-8f)
      : step_(step), factor_(factor), stop_factor_lr_(stop_factor_lr) {}

  float GetLR(unsigned num_update) override {
    while (num_update > unsigned(count_ + step_)) {
      count_ += step_;
      base_lr_ *= factor_;
      if (base_lr_ < stop_factor_lr_) base_lr_ = stop_factor_lr_;
    }
    return base_lr_;
  }

 private:
  int count_ = 0;
  int step_;
  float factor_;
  float stop_factor_lr_;
};

}  // namespace cpp
}  // namespace mxnet

#endif  // MXNET_TPU_CPP_INITIALIZER_H_
