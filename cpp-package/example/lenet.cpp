/*
 * cpp-package example: LeNet on MNIST, built ENTIRELY from the
 * generated per-op factories (op.h), fed by MXDataIter(MNISTIter) and
 * trained with OptimizerRegistry SGD — the reference's
 * cpp-package/example/lenet.cpp workflow.
 */
#include <cmath>
#include <cstdio>
#include <random>
#include <vector>

#include "mxnet-cpp/MxNetCpp.h"
#include "mxnet-cpp/MxDataIter.h"
#include "mxnet-cpp/op.h"
#include "mxnet-cpp/optimizer.h"

using namespace mxnet::cpp;

int main() {
  const int batch = 64, n_class = 10;
  Context ctx = Context::cpu();

  Symbol data = Symbol::Variable("data");
  Symbol label = Symbol::Variable("softmax_label");
  Symbol c1w = Symbol::Variable("c1_weight"), c1b = Symbol::Variable("c1_bias");
  Symbol c2w = Symbol::Variable("c2_weight"), c2b = Symbol::Variable("c2_bias");
  Symbol f1w = Symbol::Variable("f1_weight"), f1b = Symbol::Variable("f1_bias");
  Symbol f2w = Symbol::Variable("f2_weight"), f2b = Symbol::Variable("f2_bias");

  Symbol conv1 = Convolution("c1", data, c1w, c1b, Shape{5, 5}, Shape(),
                             Shape(), Shape(), 8);
  Symbol tanh1 = Activation("t1", conv1, "tanh");
  Symbol pool1 = Pooling("p1", tanh1, Shape{2, 2}, "max", Shape{2, 2});
  Symbol conv2 = Convolution("c2", pool1, c2w, c2b, Shape{5, 5}, Shape(),
                             Shape(), Shape(), 16);
  Symbol tanh2 = Activation("t2", conv2, "tanh");
  Symbol pool2 = Pooling("p2", tanh2, Shape{2, 2}, "max", Shape{2, 2});
  Symbol flat = Flatten("flat", pool2);
  Symbol fc1 = FullyConnected("f1", flat, f1w, f1b, 64);
  Symbol tanh3 = Activation("t3", fc1, "tanh");
  Symbol fc2 = FullyConnected("f2", tanh3, f2w, f2b, n_class);
  Symbol net = SoftmaxOutput("softmax", fc2, label);

  /* parameter arrays in list_arguments order */
  std::vector<std::string> arg_names = net.ListArguments();
  std::vector<Shape> shapes = {
      {(mx_uint)batch, 1, 28, 28},                 /* data */
      {8, 1, 5, 5}, {8},                           /* c1 */
      {16, 8, 5, 5}, {16},                         /* c2 */
      {64, 16 * 4 * 4}, {64},                      /* f1 (28->24->12->8->4) */
      {(mx_uint)n_class, 64}, {(mx_uint)n_class},  /* f2 */
      {(mx_uint)batch},                            /* label */
  };
  if (arg_names.size() != shapes.size()) {
    std::fprintf(stderr, "unexpected arg count %zu\n", arg_names.size());
    return 1;
  }
  std::mt19937 rng(7);
  std::vector<NDArray> args, grads;
  for (size_t i = 0; i < shapes.size(); ++i) {
    args.emplace_back(shapes[i], ctx);      /* zero-initialized */
    grads.emplace_back(shapes[i], ctx);
    if (arg_names[i].find("weight") != std::string::npos) {
      size_t n = args.back().Size();
      float scale = std::sqrt(3.f / (float)(n / shapes[i][0]));
      std::uniform_real_distribution<float> u(-scale, scale);
      std::vector<float> init(n);
      for (auto &v : init) v = u(rng);
      args.back().SyncCopyFromCPU(init.data(), init.size());
    }
  }
  /* grad only for parameters, not data/label */
  std::vector<mx_uint> reqs(shapes.size(), 1);
  reqs.front() = 0;
  reqs.back() = 0;

  Executor exec(net, ctx, &args, &grads, reqs);

  std::unique_ptr<Optimizer> opt(OptimizerRegistry::Find("sgd"));
  opt->SetParam("lr", 0.1f)->SetParam("momentum", 0.9f)
     ->SetParam("wd", 1e-4f)
     ->SetParam("rescale_grad", 1.0f / (float)batch);

  MXDataIter iter("MNISTIter");
  iter.SetParam("batch_size", batch).SetParam("silent", 1)
      .CreateDataIter();

  float first_acc = -1.f, acc = 0.f;
  for (int epoch = 0; epoch < 3; ++epoch) {
    int correct = 0, total = 0, batches = 0;
    iter.BeforeFirst();
    while (iter.Next() && batches < 40) {
      NDArray x = iter.GetData();
      NDArray y = iter.GetLabel();
      std::vector<float> xv = x.AsVector(), yv = y.AsVector();
      args[0].SyncCopyFromCPU(xv.data(), xv.size());
      args.back().SyncCopyFromCPU(yv.data(), yv.size());
      exec.Forward(true);
      exec.Backward();
#ifdef LENET_DEBUG
      if (batches == 0) {
        for (size_t i = 0; i < args.size(); ++i) {
          double gn = 0;
          for (float v : grads[i].AsVector()) gn += (double)v * v;
          std::printf("arg %zu %s grad_norm %.6f\n", i,
                      arg_names[i].c_str(), std::sqrt(gn));
        }
      }
#endif
      for (size_t i = 1; i + 1 < args.size(); ++i)
        opt->Update((int)i, &args[i], grads[i]);
      std::vector<NDArray> outs = exec.Outputs();
      std::vector<float> probs = outs[0].AsVector();
      for (int b = 0; b < batch; ++b) {
        int best = 0;
        for (int c = 1; c < n_class; ++c)
          if (probs[b * n_class + c] > probs[b * n_class + best]) best = c;
        correct += (best == (int)yv[b]);
        ++total;
      }
      ++batches;
    }
    acc = (float)correct / (float)total;
    if (first_acc < 0) first_acc = acc;
    std::printf("epoch %d acc %.3f\n", epoch, acc);
  }
  if (!(acc > 0.8f && acc > first_acc)) {
    std::fprintf(stderr, "did not learn: first %.3f last %.3f\n",
                 first_acc, acc);
    return 1;
  }
  std::printf("cpp-package lenet ok\n");
  return 0;
}
