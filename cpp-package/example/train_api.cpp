/*
 * cpp-package example: the training-support surface — Xavier
 * initializer, OptimizerRegistry (adagrad/adadelta), Accuracy/LogLoss
 * metrics, FactorScheduler — on the synthetic MLP task.
 *
 * Reference: cpp-package/example/* use the same classes from
 * initializer.h / optimizer.h / metric.h / lr_scheduler.h.
 */
#include <cmath>
#include <cstdio>
#include <memory>
#include <random>
#include <vector>

#include "mxnet-cpp/MxNetCpp.h"
#include "mxnet-cpp/initializer.h"
#include "mxnet-cpp/metric.h"
#include "mxnet-cpp/optimizer.h"

using namespace mxnet::cpp;

int main() {
  const mx_uint batch = 64, in_dim = 8, hidden = 16, n_class = 2;
  Context ctx = Context::cpu();

  Symbol x = Symbol::Variable("x");
  Symbol label = Symbol::Variable("label");
  Symbol w1 = Symbol::Variable("w1"), b1 = Symbol::Variable("b1");
  Symbol w2 = Symbol::Variable("w2"), b2 = Symbol::Variable("b2");
  Symbol fc1 = Operator("FullyConnected").SetParam("num_hidden", hidden)
                   .SetInput("data", x).SetInput("weight", w1)
                   .SetInput("bias", b1).CreateSymbol("fc1");
  Symbol act1 = Operator("Activation").SetParam("act_type", "relu")
                    .SetInput("data", fc1).CreateSymbol("relu1");
  Symbol fc2 = Operator("FullyConnected").SetParam("num_hidden", n_class)
                   .SetInput("data", act1).SetInput("weight", w2)
                   .SetInput("bias", b2).CreateSymbol("fc2");
  Symbol loss = Operator("SoftmaxOutput").SetInput("data", fc2)
                    .SetInput("label", label).CreateSymbol("softmax");

  std::mt19937 rng(7);
  std::normal_distribution<float> dist(0.f, 1.f);
  std::vector<float> xs(batch * in_dim), ys(batch);
  for (mx_uint i = 0; i < batch; ++i) {
    float s = 0;
    for (mx_uint j = 0; j < in_dim; ++j) {
      xs[i * in_dim + j] = dist(rng);
      s += (j < in_dim / 2 ? 1.f : -1.f) * xs[i * in_dim + j];
    }
    ys[i] = s > 0 ? 1.f : 0.f;
  }

  std::vector<NDArray> args;
  args.push_back(NDArray(xs, Shape{batch, in_dim}, ctx));       /* x */
  args.push_back(NDArray(Shape{hidden, in_dim}, ctx));          /* w1 */
  args.push_back(NDArray(Shape{hidden}, ctx));                  /* b1 */
  args.push_back(NDArray(Shape{n_class, hidden}, ctx));         /* w2 */
  args.push_back(NDArray(Shape{n_class}, ctx));                 /* b2 */
  args.push_back(NDArray(ys, Shape{batch}, ctx));               /* label */

  /* initializer.h: name-dispatched Xavier (biases -> 0) */
  Xavier xavier;
  auto arg_names = loss.ListArguments();
  for (size_t i = 1; i + 1 < args.size(); ++i)
    xavier(arg_names[i] == "w1" || arg_names[i] == "w2"
               ? "fc_weight" : "fc_bias", &args[i]);

  std::vector<NDArray> grads;
  std::vector<mx_uint> reqs;
  for (size_t i = 0; i < args.size(); ++i) {
    grads.emplace_back(args[i].GetShape(), ctx);
    bool is_param = arg_names[i] != "x" && arg_names[i] != "label";
    reqs.push_back(is_param ? 1 : 0);
  }
  Executor exec(loss, ctx, &args, &grads, reqs);

  std::unique_ptr<Optimizer> adagrad(OptimizerRegistry::Find("adagrad"));
  std::unique_ptr<Optimizer> adadelta(OptimizerRegistry::Find("adadelta"));
  adagrad->SetParam("eps", 1e-7f);
  adadelta->SetParam("rho", 0.9f)->SetParam("epsilon", 1e-4f);
  FactorScheduler sched(20, 0.5f);
  sched.SetLR(0.3f);

  for (int step = 0; step < 80; ++step) {
    exec.Forward(true);
    exec.Backward();
    adagrad->SetParam("lr", sched.GetLR(step));
    for (size_t i = 0; i < args.size(); ++i) {
      if (reqs[i] == 0) continue;
      /* adagrad on layer 1, adadelta on layer 2 — both paths covered */
      Optimizer *opt = (i <= 2) ? adagrad.get() : adadelta.get();
      opt->Update((int)i, &args[i], grads[i]);
    }
  }

  exec.Forward(false);
  auto outs = exec.Outputs();
  Accuracy acc;
  LogLoss ll;
  acc.Update(args[5], outs[0]);
  ll.Update(args[5], outs[0]);
  std::printf("accuracy=%.3f logloss=%.3f\n", acc.Get(), ll.Get());
  if (acc.Get() < 0.9f) {
    std::printf("TRAIN_API_FAIL\n");
    return 1;
  }
  std::printf("TRAIN_API_OK\n");
  return 0;
}
