/*
 * cpp-package example: 2-layer MLP trained on a synthetic linearly
 * separable problem, pure C++ call site.
 *
 * Reference: cpp-package/example/mlp.cpp (same structure: build symbols
 * with Operator, bind, SGD loop with manual weight update).
 */
#include <cmath>
#include <cstdio>
#include <random>
#include <vector>

#include "mxnet-cpp/MxNetCpp.h"

using namespace mxnet::cpp;

int main() {
  const int batch = 64, in_dim = 8, hidden = 16, n_class = 2;
  Context ctx = Context::cpu();

  Symbol x = Symbol::Variable("x");
  Symbol label = Symbol::Variable("label");
  Symbol w1 = Symbol::Variable("w1"), b1 = Symbol::Variable("b1");
  Symbol w2 = Symbol::Variable("w2"), b2 = Symbol::Variable("b2");

  Symbol fc1 = Operator("FullyConnected")
                   .SetParam("num_hidden", hidden)
                   .SetInput("data", x)
                   .SetInput("weight", w1)
                   .SetInput("bias", b1)
                   .CreateSymbol("fc1");
  Symbol act1 = Operator("Activation")
                    .SetParam("act_type", "relu")
                    .SetInput("data", fc1)
                    .CreateSymbol("relu1");
  Symbol fc2 = Operator("FullyConnected")
                   .SetParam("num_hidden", n_class)
                   .SetInput("data", act1)
                   .SetInput("weight", w2)
                   .SetInput("bias", b2)
                   .CreateSymbol("fc2");
  Symbol loss = Operator("SoftmaxOutput")
                    .SetInput("data", fc2)
                    .SetInput("label", label)
                    .CreateSymbol("softmax");

  /* synthetic data: class = (sum of first half > sum of second half) */
  std::mt19937 rng(7);
  std::normal_distribution<float> dist(0.f, 1.f);
  std::vector<float> xs(batch * in_dim), ys(batch);
  for (int i = 0; i < batch; ++i) {
    float s = 0;
    for (int j = 0; j < in_dim; ++j) {
      xs[i * in_dim + j] = dist(rng);
      s += (j < in_dim / 2 ? 1.f : -1.f) * xs[i * in_dim + j];
    }
    ys[i] = s > 0 ? 1.f : 0.f;
  }

  auto init = [&](const Shape &shape) {
    size_t n = 1;
    for (auto d : shape) n *= d;
    std::vector<float> v(n);
    for (auto &e : v) e = dist(rng) * 0.1f;
    return NDArray(v, shape, ctx);
  };

  std::vector<NDArray> args;
  args.push_back(NDArray(xs, Shape{batch, in_dim}, ctx));       /* x */
  args.push_back(init(Shape{hidden, in_dim}));                  /* w1 */
  args.push_back(init(Shape{hidden}));                          /* b1 */
  args.push_back(init(Shape{n_class, hidden}));                 /* w2 */
  args.push_back(init(Shape{n_class}));                         /* b2 */
  args.push_back(NDArray(ys, Shape{batch}, ctx));               /* label */

  std::vector<NDArray> grads;
  std::vector<mx_uint> reqs;
  auto arg_names = loss.ListArguments();
  for (size_t i = 0; i < args.size(); ++i) {
    grads.emplace_back(args[i].GetShape(), ctx);
    bool is_param = arg_names[i] != "x" && arg_names[i] != "label";
    reqs.push_back(is_param ? 1 : 0);
  }

  Executor exec(loss, ctx, &args, &grads, reqs);

  const float lr = 0.1f;
  float first_loss = -1, last_loss = -1;
  for (int iter = 0; iter < 50; ++iter) {
    exec.Forward(true);
    auto outs = exec.Outputs();
    auto probs = outs[0].AsVector();
    float nll = 0;
    for (int i = 0; i < batch; ++i)
      nll += -std::log(std::max(probs[i * n_class + (int)ys[i]], 1e-8f));
    nll /= batch;
    if (iter == 0) first_loss = nll;
    last_loss = nll;
    exec.Backward();
    /* SGD on the parameter args */
    for (size_t i = 0; i < args.size(); ++i) {
      if (reqs[i] == 0) continue;
      auto w = args[i].AsVector();
      auto g = grads[i].AsVector();
      for (size_t j = 0; j < w.size(); ++j) w[j] -= lr * g[j];
      args[i].SyncCopyFromCPU(w.data(), w.size());
    }
  }
  printf("loss: %.4f -> %.4f\n", first_loss, last_loss);
  if (!(last_loss < first_loss * 0.7f)) {
    fprintf(stderr, "FAIL: loss did not decrease enough\n");
    return 1;
  }
  printf("cpp-package mlp ok\n");
  return 0;
}
